"""Sharded-program contract checker tests.

Fast tier: the contract passes exercised in-process over the fixture
kernels in ``tests/shardcheck_fixtures.py`` (the suite already runs
with 8 forced host devices, so the genuine 8-way mesh is available
without a child interpreter), the golden round-trip/drift machinery,
the ``donated-read-after-dispatch`` AST check, the per-equivalent-mesh
program cache regression, and the bench/CLI wiring.  One subprocess
smoke proves the forced-environment child end to end.

Slow tier: the full golden-match pass — every real sharded kernel
traced in the child and held to the checked-in
``analysis/shard_fingerprints.json`` (the same pass as
``python scripts/lint.py --check sharding``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import shardcheck_fixtures as fx
from cometbft_tpu.analysis import (
    donated_read,
    kernel_manifest as manifest,
    linter,
    shardcheck,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _findings_for(name, findings):
    return [f for f in findings if f"[{name}]" in f.message]


def _trace_one(sk, *, rows=fx.KERNEL_ROWS):
    findings, traces = shardcheck.run_check(
        sharded=(sk,), kernel_rows=rows, skip_goldens=True
    )
    assert len(traces) <= 1
    return findings, (traces[0] if traces else None)


# ------------------------------------------------- manifest consistency


def test_sharding_manifest_is_internally_consistent():
    assert shardcheck._manifest_findings() == []
    rows = manifest.by_name()
    for sk in manifest.SHARDED_KERNELS:
        row = rows[sk.name]
        assert row.needs_mesh, sk.name
        assert len(sk.in_specs) == len(sk.args)
        assert len(sk.out_specs) == len(sk.out)
    assert set(manifest.sharded_by_name()) == {
        "sharded_verify_batch", "sharded_verify_cached", "sharded_merkle_root",
        "sharded_merkle_proofs",
    }
    # the donated-entrypoint worklist the AST check consumes: since
    # PR 11 every per-call staging slab of every sharded program is
    # donated ("finish the set"), not just the comb payload
    assert manifest.donated_entrypoints() == {
        "sharded_verify_batch": (
            ("a_enc", 1), ("r_enc", 2), ("s_bytes", 3),
            ("msg_blocks", 4), ("msg_active", 5),
        ),
        "sharded_verify_cached": (("payload", 4),),
        "sharded_merkle_root": (("leaf_blocks", 1), ("leaf_active", 2)),
        "sharded_merkle_proofs": (("indices", 3), ("sib_pos", 4)),
    }


def test_spec_normalization():
    assert shardcheck.declared_spec_map(("sig",)) == {"0": "sig"}
    assert shardcheck.declared_spec_map((None, None, "sig")) == {"2": "sig"}
    assert shardcheck.declared_spec_map(()) == {}
    assert shardcheck.traced_names_map({0: ("sig",)}) == {"0": "sig"}
    assert shardcheck.traced_names_map({}) == {}
    assert shardcheck.traced_names_map({1: ("a", "b")}) == {"1": "a+b"}
    assert shardcheck._fmt_spec({}) == "replicated"
    assert "0:sig" in shardcheck._fmt_spec({"0": "sig"})


def test_collective_prim_matcher():
    for name in ("psum", "all_gather", "all_to_all", "ppermute",
                 "sharding_constraint", "all_gather_invariant"):
        assert shardcheck.is_collective(name), name
    for name in ("add", "scan", "shard_map", "pjit", "convert_element_type"):
        assert not shardcheck.is_collective(name), name


# ----------------------------------------- contract passes (fixtures)


def test_clean_fixture_traces_green():
    findings, t = _trace_one(fx.CLEAN)
    assert findings == [], [f.message for f in findings]
    assert t.collectives == {"psum": 1}
    assert t.in_specs == [{"0": "sig"}] and t.out_specs == [{}]
    assert t.donated == [] and t.eqns > 0


def test_undeclared_collective_is_a_finding():
    findings, _ = _trace_one(fx.BAD_CENSUS)
    assert len(findings) == 1
    msg = findings[0].message
    assert "undeclared collective 'ppermute'" in msg and "(+1)" in msg
    assert findings[0].check == "shard-contract"


def test_blown_equation_budget_is_a_finding():
    """The jit_build_a_tables class: an unrolled table build fails the
    static budget with the kernel name and the delta in the report."""
    findings, t = _trace_one(fx.BAD_BUDGET)
    assert len(findings) == 1
    msg = findings[0].message
    assert "[shardfix_budget]" in msg and "compile-cost budget" in msg
    assert f"{t.eqns} jaxpr equations exceeds the budget of 64" in msg
    assert f"(+{t.eqns - 64})" in msg


def test_blown_loop_depth_is_a_finding():
    findings, _ = _trace_one(fx.BAD_DEPTH)
    assert len(findings) == 1
    assert "control-flow nesting depth 2 exceeds the budget of 1" in (
        findings[0].message
    )


def test_violated_donation_is_a_finding():
    findings, _ = _trace_one(fx.BAD_DONATION)
    assert len(findings) == 1
    assert "declared donated but the lowered program does not donate" in (
        findings[0].message
    )


def test_undeclared_donation_is_a_finding():
    findings, _ = _trace_one(fx.SNEAKY_DONATION)
    assert len(findings) == 1
    assert "donated by the lowered program but not declared" in (
        findings[0].message
    )


def test_spec_mismatch_is_a_finding():
    findings, _ = _trace_one(fx.BAD_SPEC)
    assert len(findings) == 1
    msg = findings[0].message
    assert "sharding closure" in msg
    assert "replicated" in msg and "{0:sig}" in msg


def test_inter_stage_reshard_trips_census():
    """PR-11 regression: a pipelined stage handoff that inserts a
    resharding sharding_constraint is a census finding — the
    no-reshard stage-handoff contract of docs/sharding_contracts.md."""
    findings, t = _trace_one(fx.BAD_PIPELINE)
    msgs = " | ".join(f.message for f in findings)
    assert "undeclared collective 'sharding_constraint'" in msgs
    assert t.collectives.get("sharding_constraint", 0) >= 1
    # the two-stage shape also violates the one-mesh-entry contract
    assert "shard_map applications in one program" in msgs


def test_real_sharded_programs_census_is_reshard_free():
    """The checked-in goldens carry the production censuses: zero
    sharding_constraint anywhere — pipelined stages hand off
    device-resident buffers without a resharding copy — and the
    donation vectors match the manifest's finished set (PR 11: every
    per-call staging slab donated).  The slow golden gate proves these
    goldens match a fresh 8-way trace."""
    golden = shardcheck.load_fingerprints()
    by_name = manifest.sharded_by_name()
    assert set(golden) == set(by_name)
    for name, fp in golden.items():
        assert "sharding_constraint" not in fp["collectives"], name
        assert fp["donated"] == sorted(by_name[name].donate_argnums), name
    assert golden["sharded_verify_batch"]["donated"] == [0, 1, 2, 3, 4]
    assert golden["sharded_merkle_root"]["donated"] == [0, 1]
    assert golden["sharded_verify_cached"]["donated"] == [3]


def test_untraceable_fixture_reports_trace_failure_only(tmp_path):
    findings, t = _trace_one(fx.UNTRACEABLE)
    assert len(findings) == 1
    assert "failed to trace under the 8-way mesh" in findings[0].message
    # and produces no drift noise against any golden
    assert shardcheck.compare_fingerprints([t], {"shardfix_boom": {}}) == []


def test_budget_fixture_donation_still_checked_via_pjit_alignment():
    """The real comb kernel's shape: donation index must align with the
    USER args even though the shard_map sees hoisted constants first —
    pinned here by the real manifest golden carrying donated=[3]."""
    golden = shardcheck.load_fingerprints()
    assert golden["sharded_verify_cached"]["donated"] == [3]
    assert golden["sharded_verify_cached"]["in_specs"][0] == {"4": "sig"}


# --------------------------------------------------- golden round trip


def test_golden_round_trip_and_signature_drift(tmp_path):
    p = str(tmp_path / "shard_fp.json")
    findings, traces = shardcheck.regenerate(
        p, sharded=(fx.CLEAN,), kernel_rows=fx.KERNEL_ROWS
    )
    assert findings == [] and os.path.exists(p)
    findings, _ = shardcheck.run_check(
        p, sharded=(fx.CLEAN,), kernel_rows=fx.KERNEL_ROWS
    )
    assert findings == []
    # the same kernel traced at a different width: signature drift only
    findings, _ = shardcheck.run_check(
        p, sharded=(fx.CLEAN_WIDE,), kernel_rows=fx.KERNEL_ROWS
    )
    assert len(findings) == 1
    msg = findings[0].message
    assert findings[0].check == "shard-fingerprint" and "drifted" in msg
    assert "signature before" in msg and "signature after" in msg
    assert "regen-shardings" in msg  # the operator hint


def test_regenerate_refuses_contract_findings(tmp_path):
    p = str(tmp_path / "shard_fp.json")
    findings, _ = shardcheck.regenerate(
        p, sharded=(fx.BAD_CENSUS,), kernel_rows=fx.KERNEL_ROWS
    )
    assert findings and not os.path.exists(p)


def test_missing_and_stale_goldens(tmp_path):
    _, traces = shardcheck.run_check(
        sharded=(fx.CLEAN,), kernel_rows=fx.KERNEL_ROWS, skip_goldens=True
    )
    found = shardcheck.compare_fingerprints(traces, {})
    assert len(found) == 1 and "no checked-in golden" in found[0].message
    golden = {
        "shardfix_clean": traces[0].fingerprint(),
        "ghost": {"digest": "whatever"},
    }
    found = shardcheck.compare_fingerprints(traces, golden)
    assert len(found) == 1 and "'ghost'" in found[0].message


def test_costs_ride_the_golden_but_not_the_digest(tmp_path):
    _, traces = shardcheck.run_check(
        sharded=(fx.CLEAN,), kernel_rows=fx.KERNEL_ROWS, skip_goldens=True
    )
    fp = traces[0].fingerprint()
    assert fp["costs"]["eqns"] == traces[0].eqns
    mutated = dict(fp)
    mutated["costs"] = {"eqns": 10**6, "loop_depth": 99, "device_bytes": 0}
    assert shardcheck.compare_fingerprints(
        traces, {"shardfix_clean": mutated}
    ) == []  # budget numbers are manifest-gated, not drift-gated


# -------------------------------------------- per-equivalent-mesh cache


def test_one_program_per_equivalent_mesh():
    """The PR-6 cache fix: two make_mesh calls over the same devices
    hand out the SAME program object — one trace, one compile — while a
    different axis name or comb path keys a different program."""
    from cometbft_tpu.parallel import verify as PV
    from cometbft_tpu.parallel.mesh import make_mesh, mesh_cache_key

    m1, m2 = make_mesh(1), make_mesh(1)
    assert m1 is not m2 or mesh_cache_key(m1) == mesh_cache_key(m2)
    assert PV._verify_fn(m1) is PV._verify_fn(m2)
    assert PV._merkle_fn(m1) is PV._merkle_fn(m2)
    assert PV._comb_verify_fn(m1, True) is PV._comb_verify_fn(m2, True)
    # knob flag and axis name are part of the key
    assert PV._comb_verify_fn(m1, True) is not PV._comb_verify_fn(m1, False)
    other = make_mesh(1, axis="other")
    assert PV._verify_fn(other) is not PV._verify_fn(m1)


def test_mesh_cache_key_is_stable_and_distinguishing():
    from cometbft_tpu.parallel.mesh import make_mesh, mesh_cache_key

    k1 = mesh_cache_key(make_mesh(1))
    k2 = mesh_cache_key(make_mesh(1))
    assert k1 == k2 and hash(k1) == hash(k2)
    assert mesh_cache_key(make_mesh(1, axis="x")) != k1


# ------------------------------------------ donated-read-after-dispatch


def _mod(src: str, path: str = "cometbft_tpu/models/fake.py") -> linter.Module:
    return linter.Module(path, src)


def test_donated_read_flags_read_after_dispatch():
    src = '''
def go(mesh, tables, valid, pubs):
    payload = build()
    out = sharded_verify_cached(mesh, tables, valid, pubs, payload)
    return out, payload.sum()
'''
    found = donated_read.check(_mod(src))
    assert len(found) == 1
    assert "'payload' was donated to sharded_verify_cached()" in found[0].message
    assert found[0].check == "donated-read-after-dispatch"


def test_donated_read_keyword_form_and_rebinding():
    src = '''
def kw(mesh, t, v, p):
    payload = build()
    sharded_verify_cached(mesh, t, v, p, payload=payload)
    return payload  # finding: kwarg donation

def rebound(mesh, t, v, p):
    payload = build()
    sharded_verify_cached(mesh, t, v, p, payload)
    payload = build()  # fresh buffer: taint cleared
    return payload
'''
    found = donated_read.check(_mod(src))
    assert len(found) == 1 and found[0].line == 5


def test_donated_read_flags_rhs_of_rebinding_assignment():
    """`payload = payload.sum()` reads the donated buffer BEFORE the
    rebind — Python evaluation order, not AST field order."""
    src = '''
def rebind(mesh, t, v, p):
    payload = build()
    sharded_verify_cached(mesh, t, v, p, payload)
    payload = payload.sum()  # finding: RHS reads the donated buffer
    return payload           # no finding: rebound above

def aug(mesh, t, v, p):
    payload = build()
    sharded_verify_cached(mesh, t, v, p, payload)
    payload += 1  # finding: augmented assignment reads, then rebinds
    return payload
'''
    found = donated_read.check(_mod(src))
    assert [f.line for f in found] == [5, 11], [f.render() for f in found]


def test_donated_read_exempts_prior_reads_inline_args_and_other_fns():
    src = '''
def ok(mesh, t, v, p):
    payload = build()
    use(payload)  # before dispatch: fine
    return sharded_verify_cached(mesh, t, v, p, payload)

def inline(mesh, t, v, p, slab):
    # the production pattern: the donated value is never bound
    return sharded_verify_cached(mesh, t, v, p, jnp.asarray(slab))

def unrelated(payload):
    other_call(payload)
    return payload.sum()
'''
    assert donated_read.check(_mod(src)) == []


def test_donated_read_tracks_same_scope_partial_alias():
    """The production binding shape: a functools.partial over the
    entrypoint shifts the donated position by the bound args."""
    src = '''
import functools

def aliased(mesh, t, v, p):
    fn = functools.partial(sharded_verify_cached, mesh)
    payload = build()
    fn(t, v, p, payload)
    return payload.sum()  # finding: donated via the alias

def alias_rebound(mesh, t, v, p):
    fn = functools.partial(sharded_verify_cached, mesh)
    fn = host_verify  # alias rebound: later calls are not dispatches
    payload = build()
    fn(t, v, p, payload)
    return payload.sum()
'''
    found = donated_read.check(_mod(src))
    assert len(found) == 1 and found[0].line == 8
    assert "sharded_verify_cached" in found[0].message


def test_donated_read_scopes_taints_per_function():
    src = '''
def a(mesh, t, v, p):
    payload = build()
    sharded_verify_cached(mesh, t, v, p, payload)

def b(payload):
    return payload.sum()  # different scope: no taint
'''
    assert donated_read.check(_mod(src)) == []


def test_donated_read_module_level_dispatch():
    src = (
        "payload = build()\n"
        "sharded_verify_cached(mesh, t, v, p, payload)\n"
        "print(payload.sum())\n"
    )
    found = donated_read.check(_mod(src))
    assert len(found) == 1 and found[0].line == 3


def test_donated_read_sweeps_repo_clean():
    findings, _ = linter.lint_paths(
        [os.path.join(REPO, "cometbft_tpu")],
        checks={"donated-read-after-dispatch": donated_read},
    )
    assert findings == [], "\n".join(f.render() for f in findings)


# ------------------------------------------------- child + CLI wiring


def test_subprocess_smoke_forced_8_device_child():
    """The production entry: the child really runs under 8 forced host
    devices and reports the genuine sharded trace."""
    findings, data = shardcheck.run_subprocess(
        fixtures="tests.shardcheck_fixtures",
        only=("shardfix_clean", "shardfix_census"),
        skip_goldens=True,
        timeout=300,
    )
    assert data["device_count"] == 8
    assert not data["ok"]
    msgs = [f.message for f in findings]
    assert any("undeclared collective 'ppermute'" in m for m in msgs)
    assert not any("shardfix_clean" in m for m in msgs)
    assert data["kernels"]["shardfix_clean"]["collectives"] == {"psum": 1}


def test_child_refuses_vacuous_only_filter():
    """A typo'd --only must not read as a clean pass (the PR-3
    nonexistent-lint-path rule)."""
    findings, data = shardcheck.run_subprocess(
        fixtures="tests.shardcheck_fixtures",
        only=("no_such_kernel",),
        skip_goldens=True,
        timeout=300,
    )
    assert data["ok"] is False
    assert len(findings) == 1
    assert "matched no sharded kernel" in findings[0].message


def test_run_subprocess_surfaces_child_crash(monkeypatch):
    monkeypatch.setattr(
        shardcheck.subprocess, "run",
        lambda *a, **k: subprocess.CompletedProcess(a, 3, "", "boom"),
    )
    findings, data = shardcheck.run_subprocess()
    assert len(findings) == 1 and "rc=3" in findings[0].message
    assert data["ok"] is False


def test_lint_registers_sharding_checks():
    checks = linter.all_checks()
    assert set(linter.SHARDING_CHECK_IDS) <= set(checks)
    assert checks["donated-read-after-dispatch"] is donated_read


def test_lint_cli_sharding_ast_check(tmp_path):
    bad = tmp_path / "models" / "fake.py"
    bad.parent.mkdir()
    bad.write_text(
        "def go(mesh, t, v, p):\n"
        "    payload = build()\n"
        "    sharded_verify_cached(mesh, t, v, p, payload)\n"
        "    return payload\n"
    )
    cli = [sys.executable, os.path.join(REPO, "scripts", "lint.py")]
    proc = subprocess.run(
        cli + [str(bad), "--check", "donated-read-after-dispatch", "--json"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stderr
    data = json.loads(proc.stdout)
    assert {f["check"] for f in data["findings"]} == {
        "donated-read-after-dispatch"
    }


def test_bench_reports_shardcheck(tmp_path):
    """bench.py's backend-unavailable path embeds the sharded pass —
    wire check with run_subprocess stubbed (the real pass is slow)."""
    code = (
        "import sys, json\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "import bench\n"
        "from cometbft_tpu.analysis import shardcheck\n"
        "shardcheck.run_subprocess = lambda **kw: ([], {\n"
        "    'ok': True, 'device_count': 8,\n"
        "    'kernels': {'sharded_merkle_root': {'eqns': 633}}})\n"
        "print(json.dumps(bench._shardcheck_report()))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr
    rep = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rep["ok"] is True and rep["findings"] == 0
    assert rep["kernels"] == {"sharded_merkle_root": 633}
    assert "elapsed_s" in rep


# ------------------------------------------------- compile-cache knob


def test_compile_cache_knob(tmp_path, monkeypatch):
    import jax

    from cometbft_tpu.utils import compilecache

    old_dir = jax.config.jax_compilation_cache_dir
    old_min = jax.config.jax_persistent_cache_min_compile_time_secs
    old_sz = jax.config.jax_persistent_cache_min_entry_size_bytes
    try:
        monkeypatch.delenv("COMETBFT_TPU_COMPILE_CACHE", raising=False)
        assert compilecache.maybe_enable() is None  # knob unset: no-op
        target = str(tmp_path / "xla_cache")
        monkeypatch.setenv("COMETBFT_TPU_COMPILE_CACHE", target)
        got = compilecache.maybe_enable()
        assert got == os.path.abspath(target) and os.path.isdir(target)
        assert jax.config.jax_compilation_cache_dir == got
        # default_dir is only a fallback; the knob wins
        assert compilecache.maybe_enable(default_dir="/nonexistent") == got
    finally:
        jax.config.update("jax_compilation_cache_dir", old_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", old_min)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", old_sz)


# ------------------------------------------------------- the slow gate


@pytest.mark.slow
def test_checked_in_shard_goldens_match_fresh_trace():
    """The acceptance gate: every real sharded kernel traced in the
    forced 8-device child and held to the checked-in goldens (same pass
    as ``python scripts/lint.py --check sharding`` — the child reports
    raw findings; the allowlist is the caller's job, applied here like
    the lint gate does)."""
    allowlist = linter.Allowlist.load(linter.default_allowlist_path())
    findings, data = shardcheck.run_subprocess(timeout=1200)
    findings = [f for f in findings if not allowlist.suppresses(f)]
    assert data.get("device_count") == 8, data
    assert findings == [], "\n".join(f.render() for f in findings)
    assert set(data["kernels"]) == set(manifest.sharded_by_name())
