"""CheckTx firehose soak (e2e/firehose.py).

Fast tier: a host-path smoke — every coalesced batch kept below the
device threshold, so no program compile — proving the harness end to
end: pools, storm windows, drift oracle, SLO artifact.  Slow tier: a
reduced REAL soak on the device path (prewarmed program shapes), where
the pubkey-cache hit-rate SLO is enforced — the decode cache only runs
in the device assembly loop.
"""

import json
import os

import pytest

from cometbft_tpu.e2e.firehose import (
    KEY_TYPES,
    FirehoseConfig,
    run_firehose,
)


def _smoke_cfg(tmp_path, **kw):
    base = dict(
        total_txs=48,
        senders_per_type=4,
        txs_per_sender=4,
        workers=4,
        storm_every=40,
        storm_len=8,
        slo_p99_ms=30_000.0,  # host bigint ECDSA: correctness smoke,
        # not a latency claim
        cache_check=False,  # host path never touches the decode cache
        json_path=str(tmp_path / "firehose.json"),
    )
    base.update(kw)
    return FirehoseConfig(**base)


def test_firehose_smoke_host_path(tmp_path, monkeypatch):
    monkeypatch.setenv("COMETBFT_TPU_SECP_DEVICE_MIN", "1000000")
    report = run_firehose(_smoke_cfg(tmp_path))
    assert report["ok"], report["assertions"]
    a = report["assertions"]
    assert a["completed"]["processed"] == 48
    # storm windows actually fired and every adversarial verdict matched
    # its construction-time expectation
    assert a["zero_drift"]["storm_txs"] > 0
    assert a["zero_drift"]["drift"] == []
    # all three wire shapes rode the same lane and got sampled
    for kt in KEY_TYPES:
        st = a["slo_latency"]["per_key_type"][kt]
        assert st["count"] > 0 and st["p99_ms"] is not None
    assert a["no_leak"]["drained"] is True
    # the artifact on disk is the report
    with open(tmp_path / "firehose.json") as f:
        on_disk = json.load(f)
    assert on_disk["ok"] is True
    assert on_disk["assertions"]["completed"]["processed"] == 48


def test_firehose_storm_schedule_and_artifact_dir(tmp_path, monkeypatch):
    """storm_every=0 disables storms entirely; the artifact parent dir
    is created on demand."""
    monkeypatch.setenv("COMETBFT_TPU_SECP_DEVICE_MIN", "1000000")
    path = tmp_path / "deep" / "dir" / "fh.json"
    report = run_firehose(_smoke_cfg(
        tmp_path, total_txs=24, storm_every=0, json_path=str(path),
    ))
    assert report["ok"], report["assertions"]
    assert report["assertions"]["zero_drift"]["storm_txs"] == 0
    assert os.path.exists(path)


@pytest.mark.slow
def test_firehose_reduced_device_soak(tmp_path, monkeypatch):
    """The real thing at reduced volume: device-path dispatches
    (coalesced MODE_SECP batches over all three wire shapes), storm
    windows, and the repeat-sender pubkey-cache SLO enforced from the
    verify_svc_secp_pubkey_cache_total counter.

    SECP_DEVICE_MIN drops to 2 here: on the one-core CPU backend the
    host lane drains singleton batches faster than the queue can build
    to the production threshold of 8, so at the default only ~8% of
    rows reach the device assembly loop and the 16 unavoidable
    cold-miss decodes swamp the hit-rate denominator.  At 2, every
    coalesced batch rides the device path (buckets still pad to >= 8)
    and the SLO measures what it means to: repeat senders hitting the
    decode cache."""
    monkeypatch.setenv("COMETBFT_TPU_SECP_DEVICE_MIN", "2")
    import numpy as np

    from cometbft_tpu.crypto import secp256k1 as host_secp
    from cometbft_tpu.crypto import secp256k1eth as host_eth
    from cometbft_tpu.models import secp_verifier as sv

    # prewarm the four program shapes the coalesced batches can hit
    # (buckets 8 and 16, with and without ecrecover rows) so the SLO
    # percentiles measure dispatch, not compile
    rng = np.random.default_rng(5)
    cs = [host_secp.PrivKey.from_seed(rng.bytes(32)) for _ in range(6)]
    es = [host_eth.PrivKey.from_seed(rng.bytes(32)) for _ in range(5)]
    rs = [host_eth.RecoverPrivKey.from_seed(rng.bytes(32)) for _ in range(5)]

    def batch(keys):
        out = []
        for i, sk in enumerate(keys):
            m = b"firehose warm %d" % i
            out.append((sk.pub_key().bytes(), m, sk.sign(m)))
        return out

    for shape in (
        batch(cs[:4] + es[:4]),  # bucket 8, no rec
        batch(cs[:3] + es[:2] + rs[:3]),  # bucket 8, rec
        batch(cs + es),  # bucket 16, no rec
        batch(cs + es[:2] + rs),  # bucket 16, rec
    ):
        ok, per = sv._verify_items(shape, use_device=True)
        assert ok and all(per), per

    report = run_firehose(FirehoseConfig(
        total_txs=600,
        senders_per_type=8,
        txs_per_sender=8,
        workers=32,  # deep queue: coalesced batches reach the device
        # threshold, so the decode cache actually runs
        storm_every=200,
        storm_len=25,
        batch_max=16,
        slo_p99_ms=60_000.0,
        cache_check=True,
        # production floor is 0.9 (scripts/firehose_soak.py default); at
        # 600 txs the 16 cold misses alone cost ~7% of the denominator,
        # so the reduced run keeps only a thrash-detection margin
        cache_hit_min=0.85,
        json_path=str(tmp_path / "firehose-device.json"),
    ))
    assert report["ok"], report["assertions"]
    a = report["assertions"]
    assert a["zero_drift"]["storm_txs"] > 0 and not a["zero_drift"]["drift"]
    cache = a["cache_hit_rate"]
    assert cache["lookups"] > 0 and cache["hit_rate"] >= 0.85, cache
    assert sum(report["service"]["dispatched_batches"].values()) > 0
    for kt in KEY_TYPES:
        assert a["slo_latency"]["per_key_type"][kt]["count"] > 0
