"""BlockExecutor tests: drive a real multi-height chain against the
kvstore app (mirrors reference state/execution_test.go, validation_test.go).

This is the vertical slice through the metric path: propose → (sign) →
VerifyCommit → ApplyBlock → store, minus the consensus timing loop.
"""

import pytest

from cometbft_tpu.abci import KVStoreApplication
from cometbft_tpu.abci.kvstore import default_lanes, make_val_set_change_tx
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.mempool import CListMempool, MempoolConfig
from cometbft_tpu.proxy import local_client_creator, new_app_conns
from cometbft_tpu.state.execution import (
    BlockExecutor,
    InvalidBlockError,
    build_last_commit_info,
    max_data_bytes,
    update_state,
    validate_block,
)
from cometbft_tpu.state.state import make_genesis_state
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.block_store import BlockStore
from cometbft_tpu.store.db import MemDB
from cometbft_tpu.types import block as T
from cometbft_tpu.types.event_bus import EventBus, EventQueryNewBlock, EventQueryTx
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.types.vote import Vote
from cometbft_tpu.wire import abci_pb as pb
from cometbft_tpu.wire.canonical import Timestamp

PRECOMMIT_TYPE = 2
GENESIS_NS = 1_700_000_000 * 1_000_000_000


class Harness:
    """One in-process node: app + proxy + stores + executor."""

    def __init__(self, n_vals=2, snapshot_interval=0, chain_id="exec-chain"):
        self.keys = [ed25519.PrivKey.from_seed(bytes([i + 1]) * 32) for i in range(n_vals)]
        self.genesis = GenesisDoc(
            chain_id=chain_id,
            genesis_time=Timestamp.from_unix_ns(GENESIS_NS),
            validators=[
                GenesisValidator(
                    pub_key_type="ed25519", pub_key_bytes=k.pub_key().data, power=10
                )
                for k in self.keys
            ],
            app_hash=b"\x00" * 8,  # kvstore size-0 hash
        )
        self.state = make_genesis_state(self.genesis)
        self.app = KVStoreApplication(
            lanes=default_lanes(), snapshot_interval=snapshot_interval
        )
        self.conns = new_app_conns(local_client_creator(self.app))
        self.conns.start()
        self.app.init_chain(
            pb.InitChainRequest(
                chain_id=self.genesis.chain_id,
                validators=[
                    pb.ValidatorUpdate(
                        power=10,
                        pub_key_type="ed25519",
                        pub_key_bytes=k.pub_key().data,
                    )
                    for k in self.keys
                ],
            )
        )
        self.state_store = StateStore(MemDB())
        self.state_store.bootstrap(self.state)
        self.block_store = BlockStore(MemDB())
        self.mempool = CListMempool(
            MempoolConfig(),
            self.conns.mempool,
            lane_priorities=default_lanes(),
            default_lane="default",
        )
        self.event_bus = EventBus()
        self.executor = BlockExecutor(
            self.state_store,
            self.conns.consensus,
            self.mempool,
            block_store=self.block_store,
            event_bus=self.event_bus,
        )
        self.last_block_id = None
        self.last_commit = None
        self.last_commit_ts = None

    def propose(self, height, block_time=None):
        proposer = self.state.validators.get_proposer().address
        block, part_set = self.executor.create_proposal_block(
            height, self.state, None, proposer, block_time
        )
        if height > self.state.initial_height:
            block.last_commit = self.last_commit
            block.header.last_commit_hash = b""
            block.fill_header()
            # re-cut parts: the part set must reflect the patched block,
            # or blocks reloaded from the store lose their LastCommit
            part_set = block.make_part_set()
        return block, part_set

    def commit_for(self, block, part_set, ts):
        """All validators precommit-sign the block (real signatures —
        these hit the TPU batch verifier in validate_block)."""
        bid = T.BlockID(
            hash=block.hash(),
            part_set_header=T.PartSetHeader(
                total=part_set.header.total, hash=part_set.header.hash
            ),
        )
        sigs = []
        for i, v in enumerate(self.state.validators.validators):
            key = next(k for k in self.keys if k.pub_key().address() == v.address)
            vote = Vote(
                type=PRECOMMIT_TYPE,
                height=block.header.height,
                round=0,
                block_id=bid,
                timestamp=ts,
                validator_address=v.address,
                validator_index=i,
            )
            vote.signature = key.sign(vote.sign_bytes(self.state.chain_id))
            sigs.append(vote.to_commit_sig())
        return bid, T.Commit(
            height=block.header.height, round=0, block_id=bid, signatures=sigs
        )

    def step(self, height, ts_ns):
        """Full height: propose, sign, validate+apply.

        BFT time: height h's block time must equal the weighted median of
        last_commit's timestamps (validation.go:130), so the block reuses
        the previous height's commit timestamp; this height's precommits
        are stamped ts_ns + 1s (voting happens after proposing).
        """
        commit_ts = Timestamp.from_unix_ns(ts_ns + 1_000_000_000)
        # block time: initial height uses genesis time; later heights use
        # the median commit time of last_commit
        block, part_set = self.propose(
            height, None if height == self.state.initial_height else self.last_commit_ts
        )
        bid, commit = self.commit_for(block, part_set, commit_ts)
        self.state = self.executor.apply_block(self.state, bid, block)
        self.block_store.save_block(block, part_set, commit)
        self.last_block_id = bid
        self.last_commit = commit
        self.last_commit_ts = commit_ts
        return block

    def stop(self):
        self.conns.stop()


@pytest.fixture
def harness():
    h = Harness()
    yield h
    h.stop()


def test_three_height_chain(harness):
    h = harness
    h.mempool.check_tx(b"a=1")
    b1 = h.step(1, GENESIS_NS)
    assert b1.data.txs == [b"a=1"]
    assert h.state.last_block_height == 1
    assert h.state.app_hash == b"\x02" + b"\x00" * 7  # kvstore size=1

    # the committed tx left the mempool
    assert h.mempool.size() == 0

    h.mempool.check_tx(b"b=2")
    h.mempool.check_tx(b"c=3")
    # height 2 carries a real signed LastCommit for height 1 — verify_commit
    # (the TPU-backed hot path) must pass inside apply_block
    b2 = h.step(2, GENESIS_NS + 2_000_000_000)
    assert sorted(b2.data.txs) == [b"b=2", b"c=3"]
    assert h.state.last_block_height == 2

    h.step(3, GENESIS_NS + 4_000_000_000)
    assert h.state.last_block_height == 3
    # app agrees
    info = h.conns.query.info(pb.InfoRequest())
    assert info.last_block_height == 3
    assert info.last_block_app_hash == h.state.app_hash


def test_validate_block_rejects_bad_commit(harness):
    h = harness
    h.step(1, GENESIS_NS)
    block, part_set = h.propose(2, h.last_commit_ts)
    bid, commit = h.commit_for(block, part_set, h.last_commit_ts)
    # apply_block at height 2 with a block whose last_commit has bad sigs
    # must fail commit verification
    block.last_commit = T.Commit(
        height=1,
        round=0,
        block_id=h.last_commit.block_id,
        signatures=[
            T.CommitSig(
                block_id_flag=T.BLOCK_ID_FLAG_COMMIT,
                validator_address=cs.validator_address,
                timestamp=cs.timestamp,
                signature=bytes(64),
            )
            for cs in h.last_commit.signatures
        ],
    )
    block.header.last_commit_hash = b""
    block.fill_header()
    with pytest.raises(Exception):
        h.executor.apply_block(h.state, bid, block)


def test_validator_update_via_tx(harness):
    h = harness
    h.step(1, GENESIS_NS)
    newkey = ed25519.PrivKey.from_seed(b"\x77" * 32)
    h.mempool.check_tx(make_val_set_change_tx(newkey.pub_key().data, 4))
    h.step(2, GENESIS_NS + 2_000_000_000)
    # validator set at height 4 (h+2) includes the new key
    assert h.state.next_validators.size() == 3
    assert h.state.validators.size() == 2
    h.keys.append(newkey)
    h.step(3, GENESIS_NS + 4_000_000_000)
    assert h.state.validators.size() == 3
    # state store has the historical sets
    assert h.state_store.load_validators(2).size() == 2
    assert h.state_store.load_validators(4).size() == 3


def test_events_fired_on_apply(harness):
    h = harness
    sub_block = h.event_bus.subscribe("test", EventQueryNewBlock)
    sub_tx = h.event_bus.subscribe("test2", EventQueryTx)
    h.mempool.check_tx(b"k=v")
    h.step(1, GENESIS_NS)
    msg, _ = sub_block.get(timeout=1)
    assert msg.data["block"].header.height == 1
    txmsg, tx_events = sub_tx.get(timeout=1)
    assert txmsg.data["tx"] == b"k=v"
    assert tx_events["tx.height"] == ["1"]


def test_validate_block_contextual_errors(harness):
    h = harness
    h.step(1, GENESIS_NS)
    block, part_set = h.propose(2, h.last_commit_ts)
    good_app_hash = block.header.app_hash

    block.header.app_hash = b"\xde\xad" * 16
    with pytest.raises(InvalidBlockError, match="AppHash"):
        validate_block(h.state, block)
    block.header.app_hash = good_app_hash

    # non-increasing time
    block.header.time = Timestamp.from_unix_ns(GENESIS_NS)
    with pytest.raises(InvalidBlockError, match="time"):
        validate_block(h.state, block)


def test_finalize_result_count_mismatch_detected(harness):
    class BadApp(KVStoreApplication):
        def finalize_block(self, req):
            r = super().finalize_block(req)
            r.tx_results = []
            return r

    h = harness
    bad_app = BadApp()
    conns = new_app_conns(local_client_creator(bad_app))
    conns.start()
    try:
        h.executor.proxy_app = conns.consensus
        h.mempool.check_tx(b"x=y")
        with pytest.raises(Exception, match="tx results"):
            h.step(1, GENESIS_NS)
    finally:
        conns.stop()


def test_max_data_bytes():
    assert max_data_bytes(-1, 0, 10) > 1 << 30
    with pytest.raises(Exception):
        max_data_bytes(100, 0, 1)  # too small for overhead
    assert max_data_bytes(10000, 0, 1) == 10000 - 11 - 626 - 109 - 94
