"""End-to-end batch verification: TPU kernel vs host signers.

Signatures are produced by two independent implementations (`cryptography`/
OpenSSL and the pure-Python reference) and verified by the device kernel;
corruption of any component (sig, msg, pubkey, s >= L) must be blamed on
exactly the corrupted rows (reference semantics: crypto/crypto.go:47-55,
types/validation.go:384-399).
"""

import numpy as np
import pytest

from cometbft_tpu.crypto import ed25519 as host
from cometbft_tpu.crypto import _ref25519 as ref
from cometbft_tpu.crypto.batch import create_batch_verifier, supports_batch_verifier
from cometbft_tpu.models.verifier import (
    CpuEd25519BatchVerifier,
    TpuEd25519BatchVerifier,
)

# the module's point is DEVICE verification of small batches — keep them
# off the link-aware host routing
pytestmark = pytest.mark.usefixtures("tiny_device_batches")

rng = np.random.default_rng(42)


def make_sigs(n, msg_len=120):
    out = []
    for i in range(n):
        sk = host.PrivKey.from_seed(bytes(rng.bytes(32)))
        msg = bytes(rng.bytes(msg_len))
        out.append((sk.pub_key().data, msg, sk.sign(msg)))
    return out


def test_all_valid():
    bv = TpuEd25519BatchVerifier()
    for pub, msg, sig in make_sigs(5):
        bv.add(pub, msg, sig)
    ok, each = bv.verify()
    assert ok and each == [True] * 5


def test_blame_exact_rows():
    items = make_sigs(6)
    bv = TpuEd25519BatchVerifier()
    corrupted = {1, 4}
    for i, (pub, msg, sig) in enumerate(items):
        if i in corrupted:
            msg = msg[:-1] + bytes([msg[-1] ^ 1])
        bv.add(pub, msg, sig)
    ok, each = bv.verify()
    assert not ok
    assert [not v for v in each] == [i in corrupted for i in range(6)]


def test_s_out_of_range_rejected():
    pub, msg, sig = make_sigs(1)[0]
    s = int.from_bytes(sig[32:], "little")
    bad_s = (s + ref.L).to_bytes(32, "little")  # same sig mod L, s >= L
    bv = TpuEd25519BatchVerifier()
    bv.add(pub, msg, sig[:32] + bad_s)
    ok, each = bv.verify()
    assert not ok and each == [False]


def test_matches_pure_python_reference_signer():
    seed = bytes(rng.bytes(32))
    msg = b"tpu-bft differential"
    sig = ref.sign(seed, msg)
    pub = ref.public_key(seed)
    bv = TpuEd25519BatchVerifier()
    bv.add(pub, msg, sig)
    ok, each = bv.verify()
    assert ok and each == [True]


def test_cpu_and_tpu_providers_agree():
    items = make_sigs(4)
    # corrupt one
    pub, msg, sig = items[2]
    items[2] = (pub, msg, sig[:63] + bytes([sig[63] ^ 0x40]))
    results = []
    for cls in (CpuEd25519BatchVerifier, TpuEd25519BatchVerifier):
        bv = cls()
        for pub, msg, sig in items:
            bv.add(pub, msg, sig)
        results.append(bv.verify())
    assert results[0] == results[1]
    assert results[0][1] == [True, True, False, True]


def test_factory():
    assert supports_batch_verifier("ed25519")
    bv = create_batch_verifier("ed25519")
    pub, msg, sig = make_sigs(1)[0]
    bv.add(pub, msg, sig)
    assert bv.verify() == (True, [True])


def test_variable_message_lengths():
    bv = TpuEd25519BatchVerifier()
    for ln in [0, 1, 60, 63, 64, 120, 200]:
        sk = host.PrivKey.generate()
        msg = bytes(rng.bytes(ln))
        bv.add(sk.pub_key().data, msg, sk.sign(msg))
    ok, each = bv.verify()
    assert ok and all(each)
