"""Mempool reactor: tx gossip between nodes (reference:
mempool/reactor.go, iterators.go).  The e2e case is the VERDICT
criterion: a tx submitted to a NON-validator full node is committed in a
block proposed by a validator — it can only get there over the mempool
stream."""

import threading
import time

import pytest

from cometbft_tpu.abci import KVStoreApplication
from cometbft_tpu.abci.kvstore import default_lanes
from cometbft_tpu.consensus.config import test_consensus_config
from cometbft_tpu.consensus.reactor import ConsensusReactor
from cometbft_tpu.consensus.state import ConsensusState
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.mempool import CListMempool, MempoolConfig, MempoolReactor
from cometbft_tpu.mempool.reactor import MEMPOOL_STREAM, BlockingTxIterator
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.p2p.transport import TCPTransport
from cometbft_tpu.privval import FilePV
from cometbft_tpu.privval.file_pv import FilePVKey, FilePVLastSignState
from cometbft_tpu.proxy import local_client_creator, new_app_conns
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import make_genesis_state
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.block_store import BlockStore
from cometbft_tpu.store.db import MemDB
from cometbft_tpu.types.event_bus import EventBus
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.wire import abci_pb as apb
from cometbft_tpu.wire import mempool_pb as pb
from cometbft_tpu.wire.canonical import Timestamp

GENESIS_NS = 1_700_000_000 * 1_000_000_000


# ------------------------------------------------------------- unit tests


class _Conn:
    """Minimal mempool ABCI stand-in: accepts every tx."""

    def check_tx(self, req):
        return apb.CheckTxResponse(code=0)


def _mk_mempool():
    return CListMempool(MempoolConfig(), _Conn())


def test_blocking_iterator_yields_each_live_tx_once():
    mp = _mk_mempool()
    mp.check_tx(b"a=1")
    mp.check_tx(b"b=2")
    it = BlockingTxIterator(mp)
    alive = lambda: True
    got = {it.next(alive).tx, it.next(alive).tx}
    assert got == {b"a=1", b"b=2"}
    # drained: next() blocks until a new admission arrives
    out = []
    t = threading.Thread(target=lambda: out.append(it.next(alive)), daemon=True)
    t.start()
    time.sleep(0.2)
    assert not out
    mp.check_tx(b"c=3")
    t.join(timeout=5)
    assert out and out[0].tx == b"c=3"


def test_blocking_iterator_stops_when_dead():
    mp = _mk_mempool()
    it = BlockingTxIterator(mp)
    assert it.next(lambda: False) is None


def test_receive_feeds_mempool_and_records_sender():
    mp = _mk_mempool()
    r = MempoolReactor(mp)
    r.start()

    class P:
        id = "peer-x"

    wire = pb.MempoolMessage(txs=pb.Txs(txs=[b"k=v", b"k=v"])).encode()
    r.receive(MEMPOOL_STREAM, P(), wire)  # duplicate within batch is fine
    assert mp.size() == 1
    entry = next(iter(mp.iter_entries()))
    assert entry.senders == {"peer-x"}
    r.stop()


def test_wait_sync_gates_receive_until_enabled():
    mp = _mk_mempool()
    r = MempoolReactor(mp, wait_sync=True)
    r.start()

    class P:
        id = "p"

    wire = pb.MempoolMessage(txs=pb.Txs(txs=[b"x=1"])).encode()
    r.receive(MEMPOOL_STREAM, P(), wire)
    assert mp.size() == 0  # dropped while syncing
    r.enable_in_out_txs()
    r.receive(MEMPOOL_STREAM, P(), wire)
    assert mp.size() == 1
    r.stop()


# -------------------------------------------------------------- e2e test


class Node:
    """Validator or full node with consensus + mempool reactors."""

    def __init__(self, idx, val_keys, genesis, is_validator):
        state = make_genesis_state(genesis)
        self.app = KVStoreApplication(lanes=default_lanes())
        self.conns = new_app_conns(local_client_creator(self.app))
        self.conns.start()
        self.app.init_chain(
            apb.InitChainRequest(
                chain_id=genesis.chain_id,
                validators=[
                    apb.ValidatorUpdate(
                        power=10, pub_key_type="ed25519",
                        pub_key_bytes=k.pub_key().data,
                    )
                    for k in val_keys
                ],
            )
        )
        self.state_store = StateStore(MemDB())
        self.state_store.bootstrap(state)
        self.block_store = BlockStore(MemDB())
        self.mempool = CListMempool(
            MempoolConfig(), self.conns.mempool,
            lane_priorities=default_lanes(), default_lane="default",
        )
        self.event_bus = EventBus()
        executor = BlockExecutor(
            self.state_store, self.conns.consensus, self.mempool,
            block_store=self.block_store, event_bus=self.event_bus,
        )
        cfg = test_consensus_config()
        cfg.wal_path = ""
        self.cs = ConsensusState(
            cfg, state, executor, self.block_store, self.mempool,
            event_bus=self.event_bus,
        )
        if is_validator:
            self.cs.set_priv_validator(
                FilePV(
                    key=FilePVKey(val_keys[idx]),
                    last_sign_state=FilePVLastSignState(),
                )
            )
        self.cs_reactor = ConsensusReactor(self.cs)
        self.mp_reactor = MempoolReactor(self.mempool)
        nk = NodeKey.generate(bytes([150 + idx]) * 32)
        info = NodeInfo(node_id=nk.id(), network=genesis.chain_id, moniker=f"m{idx}")
        self.switch = Switch(TCPTransport(nk, info))
        self.switch.add_reactor("CONSENSUS", self.cs_reactor)
        self.switch.add_reactor("MEMPOOL", self.mp_reactor)
        self.addr = self.switch.transport.listen("127.0.0.1:0")

    def start(self):
        self.switch.start()

    def stop(self):
        try:
            self.switch.stop()
        except Exception:
            pass
        self.conns.stop()


@pytest.mark.slow
def test_tx_submitted_to_full_node_commits_via_gossip():
    keys = [ed25519.PrivKey.from_seed(bytes([90 + i]) * 32) for i in range(3)]
    genesis = GenesisDoc(
        chain_id="mp-chain",
        genesis_time=Timestamp.from_unix_ns(GENESIS_NS),
        validators=[
            GenesisValidator(
                pub_key_type="ed25519", pub_key_bytes=k.pub_key().data, power=10
            )
            for k in keys
        ],
        app_hash=b"\x00" * 8,
    )
    # nodes 0-2 validate; node 3 is a full node — its txs MUST gossip out
    nodes = [Node(i, keys, genesis, is_validator=(i < 3)) for i in range(4)]
    for n in nodes:
        n.start()
    for i, n in enumerate(nodes):
        n.switch.dial_peer_async(nodes[(i + 1) % 4].addr, persistent=True)
    try:
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and any(
            n.switch.num_peers() < 2 for n in nodes
        ):
            time.sleep(0.1)
        # give consensus a head start so heights are flowing
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and any(
            n.cs.state.last_block_height < 1 for n in nodes
        ):
            time.sleep(0.1)

        nodes[3].mempool.check_tx(b"gossip=works")

        committed_at = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline and committed_at is None:
            h = nodes[0].block_store.height
            for height in range(1, h + 1):
                blk = nodes[0].block_store.load_block(height)
                if blk is not None and b"gossip=works" in blk.data.txs:
                    committed_at = height
                    break
            time.sleep(0.1)
        assert committed_at is not None, "tx never committed"

        blk = nodes[0].block_store.load_block(committed_at)
        # the proposer is one of the validators — NOT the submitting full
        # node, which can't propose; the tx crossed the mempool stream
        val_addrs = {k.pub_key().address() for k in keys}
        assert blk.header.proposer_address in val_addrs

        # every node's app eventually reflects the write
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            vals = [
                n.app.query(apb.QueryRequest(path="/kv", data=b"gossip")).value
                for n in nodes
            ]
            if all(v == b"works" for v in vals):
                break
            time.sleep(0.1)
        assert all(
            n.app.query(apb.QueryRequest(path="/kv", data=b"gossip")).value == b"works"
            for n in nodes
        )
    finally:
        for n in nodes:
            n.stop()
