"""P2P stack tests: secret connection, multiplexer, transport handshake,
switch (mirrors reference p2p/transport/tcp/conn/*_test.go, switch_test.go)."""

import socket
import threading
import time

import pytest

from cometbft_tpu.crypto import ed25519
from cometbft_tpu.p2p.conn.connection import MConnection, StreamDescriptor
from cometbft_tpu.p2p.conn.secret_connection import (
    SecretConnection,
    SecretConnectionError,
    make_secret_connection,
)
from cometbft_tpu.p2p.key import NodeKey
from cometbft_tpu.p2p.node_info import NodeInfo, NodeInfoError
from cometbft_tpu.p2p.reactor import Reactor
from cometbft_tpu.p2p.switch import Switch
from cometbft_tpu.p2p.transport import TCPTransport, TransportError


def _sock_pair():
    a, b = socket.socketpair()
    return a, b


def _secret_pair(key_a=None, key_b=None):
    key_a = key_a or ed25519.PrivKey.from_seed(b"\x01" * 32)
    key_b = key_b or ed25519.PrivKey.from_seed(b"\x02" * 32)
    sa, sb = _sock_pair()
    out = {}

    def server():
        out["b"] = make_secret_connection(sb, key_b)

    t = threading.Thread(target=server)
    t.start()
    ca = make_secret_connection(sa, key_a)
    t.join()
    return ca, out["b"], key_a, key_b


def test_secret_connection_roundtrip_and_identity():
    ca, cb, key_a, key_b = _secret_pair()
    # authenticated identities are the peers' real pubkeys
    assert ca.remote_pub.data == key_b.pub_key().data
    assert cb.remote_pub.data == key_a.pub_key().data
    ca.write(b"hello bft world")
    assert cb.read_exact(15) == b"hello bft world"
    # large message spans frames
    big = bytes(range(256)) * 40  # 10240 bytes
    cb.write(big)
    assert ca.read_exact(len(big)) == big


def test_secret_connection_ciphertext_not_plaintext():
    sa, sb = _sock_pair()
    key_a = ed25519.PrivKey.from_seed(b"\x03" * 32)
    key_b = ed25519.PrivKey.from_seed(b"\x04" * 32)
    raw = {}

    def server():
        conn = make_secret_connection(sb, key_b)
        conn.write(b"SECRET-PAYLOAD-1234")
        raw["done"] = True

    t = threading.Thread(target=server)
    t.start()
    ca = make_secret_connection(sa, key_a)
    # read the raw sealed frame off the socket: must not contain plaintext
    sealed = sa.recv(4096)
    assert b"SECRET-PAYLOAD-1234" not in sealed
    t.join()


def test_secret_connection_tamper_detected():
    ca, cb, *_ = _secret_pair()
    ca.write(b"x" * 10)
    # man-in-the-middle: capture the sealed frame, flip one byte, replay
    sealed = bytearray(cb._sock.recv(65536))
    sealed[8] ^= 0x01

    class FakeSock:
        def __init__(self, data):
            self.data = bytes(data)

        def recv(self, n):
            out, self.data = self.data[:n], self.data[n:]
            return out

    cb._sock = FakeSock(sealed)
    with pytest.raises(SecretConnectionError, match="authentication"):
        cb.read_exact(10)


def _mconn_pair(descs_a, descs_b, recv_a, recv_b):
    ca, cb, *_ = _secret_pair()
    ma = MConnection(ca, descs_a, recv_a, flush_throttle=0.001)
    mb = MConnection(cb, descs_b, recv_b, flush_throttle=0.001)
    ma.start()
    mb.start()
    return ma, mb


def test_mconnection_multiplexes_streams():
    got = {}
    evt = threading.Event()

    def on_b(sid, msg):
        got.setdefault(sid, []).append(msg)
        if sum(len(v) for v in got.values()) == 3:
            evt.set()

    descs = [StreamDescriptor(id=1, priority=5), StreamDescriptor(id=2, priority=1)]
    ma, mb = _mconn_pair(descs, descs, lambda s, m: None, on_b)
    try:
        assert ma.send(1, b"vote-1")
        assert ma.send(2, b"block-part")
        assert ma.send(1, b"vote-2")
        assert evt.wait(5)
        assert got[1] == [b"vote-1", b"vote-2"]
        assert got[2] == [b"block-part"]
    finally:
        ma.stop()
        mb.stop()


def test_mconnection_large_message_chunked():
    evt = threading.Event()
    got = []

    def on_b(sid, msg):
        got.append((sid, msg))
        evt.set()

    descs = [StreamDescriptor(id=7, priority=1)]
    big = bytes([i % 251 for i in range(50_000)])  # ~49 packets
    ma, mb = _mconn_pair(descs, descs, lambda s, m: None, on_b)
    try:
        assert ma.send(7, big)
        assert evt.wait(10)
        assert got[0] == (7, big)
    finally:
        ma.stop()
        mb.stop()


def test_mconnection_error_on_unknown_stream():
    errs = []
    evt = threading.Event()

    def on_err(e):
        errs.append(e)
        evt.set()

    ca, cb, *_ = _secret_pair()
    ma = MConnection(ca, [StreamDescriptor(id=1)], lambda s, m: None, flush_throttle=0.001)
    mb = MConnection(
        cb, [StreamDescriptor(id=2)], lambda s, m: None, on_error=on_err,
        flush_throttle=0.001,
    )
    ma.start()
    mb.start()
    try:
        ma.send(1, b"msg-for-unknown-stream")
        assert evt.wait(5)
        assert "unknown stream" in str(errs[0])
    finally:
        ma.stop()
        if mb.is_running():
            mb.stop()


def _make_transport(seed, chain="p2p-chain", moniker="n"):
    nk = NodeKey.generate(bytes([seed]) * 32)
    info = NodeInfo(node_id=nk.id(), network=chain, moniker=moniker, channels=bytes([1]))
    return TCPTransport(nk, info)


def test_transport_handshake_and_identity_check():
    ta = _make_transport(1)
    tb = _make_transport(2)
    addr = tb.listen("127.0.0.1:0")
    result = {}

    def server():
        result["conn"], result["info"] = tb.accept()

    t = threading.Thread(target=server)
    t.start()
    conn, info = ta.dial(addr)
    t.join()
    assert info.node_id == tb.node_key.id()
    assert result["info"].node_id == ta.node_key.id()
    conn.close()
    result["conn"].close()
    tb.close()


def test_transport_rejects_wrong_network():
    ta = _make_transport(1, chain="chain-A")
    tb = _make_transport(2, chain="chain-B")
    addr = tb.listen("127.0.0.1:0")

    def server():
        try:
            tb.accept()
        except Exception:
            pass

    t = threading.Thread(target=server)
    t.start()
    with pytest.raises((TransportError, NodeInfoError, Exception)):
        ta.dial(addr)
    t.join()
    tb.close()


class EchoReactor(Reactor):
    """Echoes every message back on the same stream."""

    def __init__(self, sid=1):
        super().__init__("echo")
        self.sid = sid
        self.received = []
        self.peers_added = []
        self.evt = threading.Event()

    def stream_descriptors(self):
        return [StreamDescriptor(id=self.sid, priority=1)]

    def add_peer(self, peer):
        self.peers_added.append(peer.id)

    def receive(self, stream_id, peer, msg_bytes):
        self.received.append(msg_bytes)
        if msg_bytes.startswith(b"ping:"):
            peer.send(stream_id, b"echo:" + msg_bytes[5:])
        self.evt.set()


def _make_switch(seed, chain="sw-chain"):
    nk = NodeKey.generate(bytes([seed]) * 32)
    info = NodeInfo(node_id=nk.id(), network=chain, moniker=f"node{seed}")
    sw = Switch(TCPTransport(nk, info))
    return sw


def test_switch_connects_two_nodes_and_routes():
    sw_a, sw_b = _make_switch(11), _make_switch(12)
    ra, rb = EchoReactor(), EchoReactor()
    sw_a.add_reactor("echo", ra)
    sw_b.add_reactor("echo", rb)
    addr_b = sw_b.transport.listen("127.0.0.1:0")
    sw_a.start()
    sw_b.start()
    try:
        sw_a.dial_peer_async(addr_b)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and sw_a.num_peers() == 0:
            time.sleep(0.05)
        assert sw_a.num_peers() == 1 and sw_b.num_peers() == 1
        # route a message: A -> B (reactor echoes) -> A
        peer_b = sw_a.peers.list()[0]
        assert peer_b.send(1, b"ping:hello")
        assert ra.evt.wait(5)
        assert b"echo:hello" in ra.received
        assert rb.peers_added and ra.peers_added
    finally:
        sw_a.stop()
        sw_b.stop()


def test_switch_broadcast_reaches_all_peers():
    center = _make_switch(21)
    rc = EchoReactor()
    center.add_reactor("echo", rc)
    others = []
    for i in (22, 23, 24):
        sw = _make_switch(i)
        r = EchoReactor()
        sw.add_reactor("echo", r)
        others.append((sw, r))
    addr = center.transport.listen("127.0.0.1:0")
    center.start()
    for sw, _ in others:
        sw.start()
        sw.dial_peer_async(addr)
    try:
        # counted == deliverable: the switch registers a peer in the
        # PeerSet only once its mconn is running (the add-before-start
        # race is fixed at the source in Switch._add_peer_conn), so the
        # moment num_peers() reports 3 a broadcast must reach all three —
        # no mconn-running probe needed
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and center.num_peers() < 3:
            time.sleep(0.05)
        assert center.num_peers() == 3
        assert all(
            p.is_running() and p.mconn.is_running()
            for p in center.peers.list()
        ), "registered peer without a running mconn (add-before-start race)"
        center.broadcast(1, b"announce")
        for _, r in others:
            assert r.evt.wait(5)
            assert b"announce" in r.received
    finally:
        center.stop()
        for sw, _ in others:
            sw.stop()


def test_peer_disconnect_removes_from_switch():
    sw_a, sw_b = _make_switch(31), _make_switch(32)
    sw_a.add_reactor("echo", EchoReactor())
    sw_b.add_reactor("echo", EchoReactor())
    addr_b = sw_b.transport.listen("127.0.0.1:0")
    sw_a.start()
    sw_b.start()
    try:
        sw_a.dial_peer_async(addr_b)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and sw_b.num_peers() == 0:
            time.sleep(0.05)
        assert sw_b.num_peers() == 1
        # hard-kill A's side; B must notice and drop the peer
        for p in sw_a.peers.list():
            sw_a.stop_peer(p, "test kill")
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and sw_b.num_peers() > 0:
            time.sleep(0.05)
        assert sw_b.num_peers() == 0
    finally:
        sw_a.stop()
        sw_b.stop()


def test_node_key_persistence(tmp_path):
    path = str(tmp_path / "node_key.json")
    nk = NodeKey.load_or_gen(path)
    nk2 = NodeKey.load_or_gen(path)
    assert nk.id() == nk2.id()
    assert len(nk.id()) == 40  # 20-byte address, hex


def test_latency_conn_shapes_flushes_and_surfaces_errors():
    """utils/netutil.LatencyConn: delayed ordered delivery, flush on
    close (acknowledged writes must reach the wire), and a dead inner
    conn surfaces to subsequent writers."""
    import time

    from cometbft_tpu.utils.netutil import LatencyConn

    class Inner:
        def __init__(self):
            self.wrote = []
            self.closed = False
            self.fail = False

        def write(self, b):
            if self.fail:
                raise OSError("broken pipe")
            self.wrote.append((time.monotonic(), bytes(b)))
            return len(b)

        def read(self, n):
            return b""

        def close(self):
            self.closed = True

    inner = Inner()
    c = LatencyConn(inner, delay_ms=40, jitter_ms=10)
    t0 = time.monotonic()
    c.write(b"a")
    c.write(b"b")
    c.close()  # must flush both before closing inner
    assert inner.closed
    assert [d for _, d in inner.wrote] == [b"a", b"b"]
    for ts, _ in inner.wrote:
        assert ts - t0 >= 0.035  # the link delay actually applied

    # pump death surfaces to the next writer instead of silently queueing
    inner2 = Inner()
    inner2.fail = True
    c2 = LatencyConn(inner2, delay_ms=1)
    c2.write(b"x")  # accepted; pump will die trying to deliver
    deadline = time.monotonic() + 2
    died = False
    while time.monotonic() < deadline:
        try:
            c2.write(b"y")
            time.sleep(0.02)
        except OSError:
            died = True
            break
    assert died, "dead pump never surfaced to writers"
