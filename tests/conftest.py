"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding/collective
code path (the multi-chip design) is exercised without real TPU hardware,
mirroring how the reference tests multi-node behavior in-process
(reference: internal/consensus/common_test.go topology).

Note: this environment injects a TPU PJRT plugin via sitecustomize, which
imports jax at interpreter start — so JAX has already snapshotted
JAX_PLATFORMS from the environment by the time this file runs.  Setting
os.environ here would be a no-op; jax.config.update is the authoritative
switch.  XLA_FLAGS is still read lazily at first backend initialization,
so the host-device-count flag can be injected here.
"""

import os

# Scrub the axon device-plugin trigger so every subprocess the tests spawn
# (e2e nodes, failpoint crash-children, remote signers) starts WITHOUT
# contacting the real TPU tunnel: the sitecustomize keyed on this var dials
# the relay at interpreter start, and tests that kill their children
# (crash-recovery, perturbations) would strand half-open device sessions —
# wedging the one-client tunnel for the benchmark that runs after the
# suite.  The pytest process itself already ran sitecustomize; dropping
# the var here only affects children, which all force JAX_PLATFORMS=cpu.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

# NOTE on COMETBFT_TPU_DEVICE_BATCH_MIN: kernel test modules pin it to 1
# locally (test_comb, test_comb_smoke, test_comb_routing, test_parallel,
# test_blocksync_replay) so tiny batches exercise the device paths under
# test.  It must NOT be forced suite-wide: in-process consensus network
# tests would then batch-verify 4-signature commits through freshly
# compiling XLA programs, stalling rounds until the liveness watchdog
# fires (observed: test_four_validator_network_commits_blocks).

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Lock-order witness (analysis/lockwitness): ON for every suite run —
# each run doubles as a deadlock hunt — unless explicitly disabled with
# COMETBFT_TPU_LOCKCHECK=0.  Installed here, after jax (whose import-time
# internals we don't want to witness) and BEFORE any other cometbft_tpu
# module import, so every lock the framework creates is wrapped.  The
# knob is read raw because importing utils.envknobs would drag in
# utils/__init__ (service, logging) ahead of the install; lockwitness
# itself is stdlib-only and exports the get_bool-mirroring spellings.
from cometbft_tpu.analysis import lockwitness as _lockwitness  # noqa: E402

_lockcheck = os.environ.get("COMETBFT_TPU_LOCKCHECK", "").strip().lower()
if _lockcheck not in _lockwitness.FALSE_SPELLINGS:
    _lockwitness.install(raise_on_violation=_lockcheck == "raise")
else:
    _lockwitness = None

# Persistent compilation cache: the Ed25519 kernel takes minutes to compile
# on the CPU backend; cache compiled executables across test runs.  Routed
# through the production knob helper (utils/compilecache) so
# COMETBFT_TPU_COMPILE_CACHE still wins — an operator can redirect or
# isolate the suite's cache without editing this file; the repo-local
# tests/.jax_cache is only the default.  (Imported after the lockwitness
# install above, so the helper's module-level locks are witnessed.)
from cometbft_tpu.utils import compilecache as _compilecache  # noqa: E402

_compilecache.maybe_enable(
    default_dir=os.path.join(os.path.dirname(__file__), ".jax_cache")
)


import pytest  # noqa: E402


@pytest.fixture
def tiny_device_batches(monkeypatch):
    """Route tiny batches onto the DEVICE kernels: modules that test
    device verification opt in via
    `pytestmark = pytest.mark.usefixtures("tiny_device_batches")` —
    the production link-aware threshold
    (models/verifier._device_batch_min) would host-route their V=4..64
    batches and silently skip the code under test.  Never force this
    suite-wide: in-process consensus tests would stall rounds behind
    XLA compiles and trip the liveness watchdog."""
    monkeypatch.setenv("COMETBFT_TPU_DEVICE_BATCH_MIN", "1")


@pytest.fixture(autouse=True)
def _watchdog_must_not_fire():
    """The consensus liveness watchdog is a production backstop for bug
    classes fixed in r4; a healthy state machine never needs it (the
    reference has no watchdog — internal/consensus/state.go:795-884).
    Fail any in-process test during which it re-kicks so regressions in
    timeout scheduling surface as the root cause, not as a silent 20 s
    hiccup the watchdog papers over."""
    from cometbft_tpu.consensus.state import ConsensusState

    before = ConsensusState.watchdog_fire_count
    yield
    after = ConsensusState.watchdog_fire_count
    assert after == before, (
        f"consensus watchdog re-kicked {after - before}x during this test: "
        "a scheduled timeout evaporated (see state.py _watchdog_routine)"
    )


@pytest.fixture(autouse=True)
def _no_lock_order_violations():
    """Fail the test during which the lock witness recorded an order
    cycle or a sleep-while-locked, pinning the blame to the scenario
    that produced it (mirrors the watchdog fixture above).  Violations
    raised by background daemon threads land on whichever test is
    running — close enough to identify the culprit."""
    if _lockwitness is None:
        yield
        return
    # snapshot by identity, not index: lockwitness.clear() (used by the
    # witness's own tests to scrub intentional violations) would strand
    # an index snapshot past the list end and mask later real violations
    before = _lockwitness.violations()  # pins the objects against id reuse
    before_ids = {id(v) for v in before}
    yield
    new = [v for v in _lockwitness.violations() if id(v) not in before_ids]
    assert not new, (
        "lock witness recorded violation(s) during this test:\n"
        + "\n".join(v.render() for v in new)
    )


def find_leaked_compile_threads(frames=None):
    """Surviving background threads parked inside JAX/XLA machinery —
    the exit-134 bug class: a daemon thread still compiling (a leaked
    comb table build, a BLS kernel trace) races interpreter teardown and
    aborts the whole run with ``terminate called without an active
    exception`` and NO blame (the PR-13 ``resolve_mode`` bug died
    exactly this way; every test had passed).  Returns
    [(thread_name, formatted_stack)].

    ``frames`` is injectable for the guard's own test; default is the
    live ``sys._current_frames()``.  Only jax/jaxlib/xla frames flag:
    the framework's long-lived daemons (verifysvc scheduler, tracing
    ring, health sentinel) idle in framework code and must not trip a
    suite-wide gate."""
    import sys as _sys
    import threading as _threading
    import traceback as _traceback

    if frames is None:
        frames = _sys._current_frames()
    offenders = []
    for t in _threading.enumerate():
        if t is _threading.main_thread() or t.ident is None:
            continue
        fr = frames.get(t.ident)
        if fr is None:
            continue
        stack = _traceback.extract_stack(fr)
        if any(
            ("/jax/" in (f.filename or ""))
            or ("jaxlib" in (f.filename or ""))
            or ("/xla" in (f.filename or ""))
            for f in stack
        ):
            offenders.append(
                (t.name, "".join(_traceback.format_list(stack)))
            )
    return offenders


def pytest_sessionfinish(session, exitstatus):
    """Tier-1 exit-134 guard: after the whole session, assert no
    non-test background compile/daemon thread survives inside JAX/XLA.
    All dots green while a background comb-table compile aborts the
    interpreter at exit was a REAL lost round — this turns that silent
    134 into a named thread with a stack."""
    offenders = find_leaked_compile_threads()
    if not offenders:
        return
    import sys as _sys

    lines = [
        "",
        "=" * 70,
        "LEAKED BACKGROUND COMPILE THREAD(S) AT SESSION END "
        "(exit-134 guard):",
        "a test kicked off device work (table build / kernel trace) and "
        "exited without draining it; interpreter teardown will race the "
        "compile and can abort the run with no blame.",
    ]
    for name, stack in offenders:
        lines.append("-" * 70)
        lines.append(f"thread: {name}")
        lines.append(stack.rstrip())
    lines.append("=" * 70)
    print("\n".join(lines), file=_sys.stderr, flush=True)
    # fail the run visibly: rc=1 with the report above beats the silent
    # SIGABRT the leak would otherwise risk.  (wrap_session returns
    # session.exitstatus AFTER this hook, so the assignment sticks.)
    session.exitstatus = max(int(exitstatus or 0), 1)


@pytest.fixture
def cpu_crypto_backend(monkeypatch):
    """Force the sequential host verifier (storage/domain-logic tests
    that don't exercise the kernel).  A fixture, NOT a module-level
    os.environ write: pytest imports every test module at collection
    time, so module-level env mutation leaks into the whole suite and
    silently reroutes other files' verifier paths."""
    monkeypatch.setenv("COMETBFT_TPU_CRYPTO_BACKEND", "cpu")
