"""Data-companion services: block / block-results / version / privileged
pruning over the socket-proto transport (reference:
rpc/grpc/server/services/*; transport substitution documented in
rpc/services.py)."""

import threading

import pytest

from cometbft_tpu.rpc.services import CompanionServiceClient, CompanionServiceServer
from cometbft_tpu.state.pruner import Pruner
from cometbft_tpu.store.db import MemDB

from test_execution import GENESIS_NS, Harness

NS = 1_000_000_000


@pytest.fixture
def net():
    h = Harness()
    for i in range(6):
        h.step(1 + i, GENESIS_NS + (1 + i) * 2 * NS)
    pruner = Pruner(MemDB(), h.state_store, h.block_store)
    # public and privileged listeners are split (rpc/services.py): the
    # pruning retain-height API lives on its own firewallable port
    srv = CompanionServiceServer(
        "127.0.0.1:0",
        h.block_store,
        h.state_store,
        event_bus=h.event_bus,
        node_version="0.1.0-test",
    )
    srv.start()
    priv = CompanionServiceServer(
        "127.0.0.1:0",
        h.block_store,
        h.state_store,
        pruner=pruner,
        event_bus=h.event_bus,
        node_version="0.1.0-test",
        privileged=True,
    )
    priv.start()
    cli = CompanionServiceClient(srv.laddr)
    pcli = CompanionServiceClient(priv.laddr)
    yield h, srv, cli, pruner, pcli
    cli.close()
    pcli.close()
    srv.stop()
    priv.stop()
    h.stop()


def test_version_service(net):
    _, _, cli, _, _ = net
    v = cli.get_version()
    assert v.node == "0.1.0-test"
    assert v.abci and v.block > 0 and v.p2p > 0


def test_block_service_get_by_height(net):
    h, _, cli, _, _ = net
    resp = cli.get_by_height(3)
    assert resp.block.header.height == 3
    assert resp.block_id.hash == h.block_store.load_block_meta(3).block_id.hash
    # height 0 = latest
    assert cli.get_by_height(0).block.header.height == 6
    with pytest.raises(RuntimeError, match="not in store range"):
        cli.get_by_height(99)


def test_block_results_service(net):
    h, _, cli, _, _ = net
    r = cli.get_block_results(4)
    assert r.height == 4
    assert r.app_hash == h.state_store.load_finalize_block_response(4).app_hash
    with pytest.raises(RuntimeError):
        cli.get_block_results(77)


def test_latest_height_stream_follows_new_blocks(net):
    h, _, cli, _, _ = net
    heights = []
    done = threading.Event()

    def consume():
        for height in cli.latest_height_stream():
            heights.append(height)
            if len(heights) >= 2:
                done.set()
                return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    # first response arrives immediately with the current height
    for _ in range(50):
        if heights:
            break
        threading.Event().wait(0.05)
    assert heights and heights[0] == 6
    h.step(7, GENESIS_NS + 7 * 2 * NS)  # fires NewBlock on the event bus
    assert done.wait(5.0), f"stream never advanced: {heights}"
    assert heights[1] == 7


def test_pruning_service_retain_heights(net):
    h, _, _, pruner, cli = net  # pruning rides the privileged listener
    cli.set_block_retain_height(4)
    got = cli.get_block_retain_height()
    assert got.pruning_service_retain_height == 4
    assert got.app_retain_height == 0
    # app never allowed pruning -> nothing prunable yet
    assert pruner.prune_once() == 0
    pruner.set_app_block_retain_height(5)
    assert pruner.prune_once() == 3  # blocks 1..3 (min(4,5))
    assert h.block_store.base == 4

    # block results prune independently, above the block retain height
    cli.set_block_results_retain_height(6)
    assert cli.get_block_results_retain_height() == 6
    pruner.prune_once()
    assert h.state_store.load_finalize_block_response(5) is None
    assert h.state_store.load_finalize_block_response(6) is not None

    cli.set_tx_indexer_retain_height(2)
    cli.set_block_indexer_retain_height(2)
    assert cli.get_tx_indexer_retain_height() == 2
    assert cli.get_block_indexer_retain_height() == 2


def test_pruning_rejected_on_public_listener(net):
    """The public listener must refuse pruning.* (privileged split —
    reference: grpc_laddr vs grpc_privileged_laddr), and the privileged
    listener must refuse the public services."""
    _, _, cli, _, pcli = net
    with pytest.raises(RuntimeError, match="not served on this listener"):
        cli.set_block_retain_height(4)
    with pytest.raises(RuntimeError, match="not served on this listener"):
        pcli.get_version()


def test_unknown_method_errors(net):
    _, srv, cli, _, _ = net
    from cometbft_tpu.wire import services_pb as spb

    with pytest.raises(RuntimeError, match="unknown method"):
        cli._call("no.SuchMethod", spb.Empty())


def test_indexer_prune():
    """TxIndexer/BlockIndexer prune drops records, height keys, and event
    keys below the retain height and keeps everything above it."""
    from cometbft_tpu.indexer.block import BlockIndexer
    from cometbft_tpu.indexer.tx import TxIndexer
    from cometbft_tpu.types.tx import tx_hash
    from cometbft_tpu.wire import abci_pb as apb

    txi = TxIndexer(MemDB())
    txs = {}
    for height in (1, 2, 3):
        tx = b"tx-%d" % height
        txs[height] = tx
        txi.index(
            height,
            0,
            tx,
            apb.ExecTxResult(code=0),
            {"tx.event": ["v%d" % height]},
        )
    assert txi.prune(3) == 2
    assert txi.get(tx_hash(txs[1])) is None
    assert txi.get(tx_hash(txs[2])) is None
    assert txi.get(tx_hash(txs[3])) is not None
    assert txi.search("tx.event = 'v2'") == []
    assert len(txi.search("tx.event = 'v3'")) == 1

    bli = BlockIndexer(MemDB())
    for height in (1, 2, 3):
        bli.index(height, {"block.event": ["b%d" % height]})
    assert bli.prune(3) == 2
    assert not bli.has(1) and not bli.has(2) and bli.has(3)
    assert bli.search("block.event = 'b1'") == []
    assert bli.search("block.event = 'b3'") == [3]
