"""Node assembly + CLI + RPC: a node is initialized from files, runs,
and is driven/observed entirely over HTTP + WebSocket (reference:
node/node_test.go, rpc/core tests; VERDICT criteria 8 and 9)."""

import json
import os
import time

import pytest

from cometbft_tpu.cli import main as cli_main
from cometbft_tpu.config import Config, load_config, save_config
from cometbft_tpu.consensus.config import test_consensus_config
from cometbft_tpu.node import Node
from cometbft_tpu.rpc import HTTPClient, WSClient


def _mk_home(tmp_path, name, chain_id="cli-chain"):
    home = str(tmp_path / name)
    assert cli_main(["--home", home, "init", "--chain-id", chain_id]) == 0
    return home


def _test_cfg(home) -> Config:
    cfg = load_config(home)
    cfg.base.db_backend = "memdb"
    cfg.consensus = test_consensus_config()
    cfg.consensus.wal_path = ""
    cfg.p2p.laddr = "tcp://127.0.0.1:0"
    cfg.rpc.laddr = "tcp://127.0.0.1:0"
    return cfg


def _wait(cond, timeout=90, tick=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(tick)
    return False


# ------------------------------------------------------------- fast tests


def test_config_toml_roundtrip(tmp_path):
    cfg = Config(home=str(tmp_path))
    cfg.base.moniker = "round-trip"
    cfg.p2p.persistent_peers = "aa@1.2.3.4:26656"
    cfg.mempool.size = 123
    cfg.consensus.timeout_propose = 1.25
    cfg.statesync.enable = False
    save_config(cfg)
    loaded = load_config(str(tmp_path))
    assert loaded.base.moniker == "round-trip"
    assert loaded.p2p.persistent_peers == "aa@1.2.3.4:26656"
    assert loaded.mempool.size == 123
    assert loaded.consensus.timeout_propose == 1.25


def test_cli_init_creates_all_files(tmp_path):
    home = _mk_home(tmp_path, "n0")
    for rel in (
        "config/config.toml",
        "config/genesis.json",
        "config/node_key.json",
        "config/priv_validator_key.json",
        "data/priv_validator_state.json",
    ):
        assert os.path.exists(os.path.join(home, rel)), rel
    # idempotent
    assert cli_main(["--home", home, "init"]) == 0
    g = json.load(open(os.path.join(home, "config/genesis.json")))
    assert g["chain_id"] == "cli-chain" and len(g["validators"]) == 1


def test_cli_testnet_generates_ring(tmp_path):
    out = str(tmp_path / "net")
    assert cli_main(["testnet", "--v", "3", "--o", out, "--chain-id", "tn"]) == 0
    genesis_files = [
        json.load(open(os.path.join(out, f"node{i}", "config/genesis.json")))
        for i in range(3)
    ]
    assert all(g == genesis_files[0] for g in genesis_files)
    assert len(genesis_files[0]["validators"]) == 3
    cfg = load_config(os.path.join(out, "node1"))
    assert cfg.p2p.persistent_peers.count("@") == 2


# -------------------------------------------------------------- e2e tests


@pytest.mark.slow
def test_node_runs_and_serves_rpc(tmp_path):
    home = _mk_home(tmp_path, "solo", chain_id="rpc-chain")
    node = Node(_test_cfg(home))
    node.start()
    try:
        rpc = HTTPClient(node.rpc_server.listen_addr)
        assert rpc.health() == {}
        assert _wait(lambda: int(rpc.status()["sync_info"]["latest_block_height"]) >= 2)
        st = rpc.status()
        assert st["node_info"]["network"] == "rpc-chain"
        assert st["sync_info"]["catching_up"] is False

        # a websocket subscriber sees new blocks as they commit
        ws = WSClient(node.rpc_server.listen_addr)
        ws.subscribe("tm.event='NewBlock'")
        ack = ws.recv()
        assert "error" not in ack
        ev = ws.recv()
        height_seen = int(
            ev["result"]["data"]["value"]["block"]["header"]["height"]
        )
        assert height_seen >= 1
        ws.close()

        # broadcast_tx_commit: tx lands in a block and the app sees it
        res = rpc.broadcast_tx_commit(b"rpc=works")
        assert res["check_tx"]["code"] == 0
        assert res["tx_result"]["code"] == 0
        committed_h = int(res["height"])
        assert committed_h >= 1

        q = rpc.abci_query("/kv", b"rpc")
        import base64

        assert base64.b64decode(q["response"]["value"]) == b"works"

        blk = rpc.block(committed_h)
        assert any(
            base64.b64decode(tx) == b"rpc=works"
            for tx in blk["block"]["data"]["txs"]
        )
        cm = rpc.commit(committed_h)
        assert cm["signed_header"]["header"]["height"] == str(committed_h)
        vals = rpc.validators()
        assert vals["total"] == "1" and len(vals["validators"]) == 1
        info = rpc.abci_info()
        assert int(info["response"]["last_block_height"]) >= committed_h

        # indexer-backed endpoints: tx by hash + tx_search by height.
        # indexing is asynchronous (IndexerService pump thread) — poll.
        tx_h = res["hash"]
        got = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                got = rpc.call("tx", hash=tx_h)
                break
            except Exception:
                time.sleep(0.1)
        assert got is not None, "tx never appeared in the indexer"
        assert got["height"] == str(committed_h)
        assert base64.b64decode(got["tx"]) == b"rpc=works"
        found = rpc.call("tx_search", query=f"tx.height={committed_h}")
        assert int(found["total_count"]) >= 1
        assert any(t["hash"] == tx_h for t in found["txs"])

        # tx?prove=true ships a Merkle proof rooted in the block's
        # data_hash (rpc/core/tx.go Tx with prove)
        proved = rpc.call("tx", hash=tx_h, prove=True)
        root = proved["proof"]["root_hash"]
        hdr = rpc.block(committed_h)["block"]["header"]
        assert root == hdr["data_hash"]
        assert int(proved["proof"]["proof"]["total"]) >= 1
        # order_by runs both directions (tx.go TxSearch order_by)
        desc = rpc.call(
            "tx_search", query=f"tx.height={committed_h}", order_by="desc"
        )
        assert int(desc["total_count"]) == int(found["total_count"])
        import pytest as _pytest

        with _pytest.raises(Exception, match="order_by"):
            rpc.call("tx_search", query="tx.height=1", order_by="sideways")
        br = rpc.call("block_results", height=committed_h)
        assert br["txs_results"][0]["code"] == 0

        # per-package call-site metrics moved during the run
        # (internal/consensus/metrics.go:33 checklist analogues)
        from cometbft_tpu.utils.metrics import hub as mhub

        text = mhub().registry.expose_text()
        assert "cometbft_consensus_round_duration_seconds_count" in text
        assert mhub().cs_validators_power.value() > 0
        assert mhub().cs_proposal_create_count.value() > 0
        assert mhub().mp_tx_size_bytes._totals != {}
        assert mhub().store_access_seconds._totals != {}
    finally:
        node.stop()


@pytest.mark.slow
def test_late_node_driven_entirely_over_http(tmp_path):
    """VERDICT criterion 9: start a validator, then a late full node
    peered to it, and drive/observe the late node purely over HTTP."""
    import shutil

    home_a = _mk_home(tmp_path, "val", chain_id="late-chain")
    home_b = _mk_home(tmp_path, "late", chain_id="late-chain")
    # the late node shares the validator's genesis (not its own)
    shutil.copy(
        os.path.join(home_a, "config/genesis.json"),
        os.path.join(home_b, "config/genesis.json"),
    )

    node_a = Node(_test_cfg(home_a))
    node_a.start()
    try:
        rpc_a = HTTPClient(node_a.rpc_server.listen_addr)
        assert _wait(lambda: int(rpc_a.status()["sync_info"]["latest_block_height"]) >= 5)

        cfg_b = _test_cfg(home_b)
        cfg_b.p2p.persistent_peers = (
            f"{node_a.node_key.id()}@{node_a.listen_addr}"
        )
        node_b = Node(cfg_b)
        node_b.start()
        try:
            rpc_b = HTTPClient(node_b.rpc_server.listen_addr)
            # observed over HTTP: catches up with the validator's chain
            assert _wait(
                lambda: int(rpc_b.status()["sync_info"]["latest_block_height"]) >= 5
                and rpc_b.status()["sync_info"]["catching_up"] is False,
                timeout=120,
            ), rpc_b.status()["sync_info"]
            assert rpc_b.net_info()["n_peers"] == "1"

            # driven over HTTP: tx submitted to the late node commits via
            # gossip to the validator
            res = rpc_b.broadcast_tx_sync(b"late=driven")
            assert res["code"] == 0
            assert _wait(
                lambda: rpc_b.abci_query("/kv", b"late")["response"]["value"] != "",
                timeout=60,
            )
            import base64

            assert (
                base64.b64decode(
                    rpc_b.abci_query("/kv", b"late")["response"]["value"]
                )
                == b"driven"
            )
            # both chains agree on the block that holds it
            hb = rpc_b.status()["sync_info"]["latest_block_height"]
            assert int(hb) > 0
        finally:
            node_b.stop()
    finally:
        node_a.stop()


@pytest.mark.slow
def test_extended_rpc_routes(tmp_path):
    """header/blockchain/by-hash/check_tx/dump_consensus_state/
    broadcast_evidence (rpc/core/{blocks,mempool,consensus,evidence}.go)."""
    home = _mk_home(tmp_path, "ext", chain_id="ext-chain")
    node = Node(_test_cfg(home))
    node.start()
    try:
        rpc = HTTPClient(node.rpc_server.listen_addr)
        assert _wait(lambda: int(rpc.status()["sync_info"]["latest_block_height"]) >= 3)

        hd = rpc.call("header", height=2)
        assert hd["header"]["height"] == "2"
        blk = rpc.block(2)
        h_hex = blk["block_id"]["hash"]
        assert rpc.call("header_by_hash", hash=h_hex)["header"]["height"] == "2"
        assert (
            rpc.call("block_by_hash", hash=h_hex)["block"]["header"]["height"]
            == "2"
        )

        bc = rpc.call("blockchain", minHeight=1, maxHeight=3)
        assert int(bc["last_height"]) >= 3
        hs = [int(m["header"]["height"]) for m in bc["block_metas"]]
        assert hs == sorted(hs, reverse=True) and set(hs) == {1, 2, 3}

        ct = rpc.call("check_tx", tx="Y2hlY2s9bWU=")  # check=me
        assert ct["code"] == 0

        dcs = rpc.call("dump_consensus_state")
        assert "round_state" in dcs and "peers" in dcs

        # broadcast_evidence: a real double-sign from this chain's key
        import base64 as b64mod

        from cometbft_tpu.privval import FilePV
        from cometbft_tpu.types.evidence import (
            DuplicateVoteEvidence,
            evidence_to_proto,
        )
        from cometbft_tpu.types.block import BlockID, PartSetHeader, Timestamp
        from cometbft_tpu.types.vote import Vote
        from cometbft_tpu.wire.canonical import PRECOMMIT_TYPE

        cfg = load_config(home)
        pv = FilePV.load_or_generate(
            cfg.priv_validator_key_file(), cfg.priv_validator_state_file()
        )
        sk = pv.key.priv_key
        addr = sk.pub_key().address()
        height = 1
        # the pool checks evidence time == the block time at that height
        meta1 = node.block_store.load_block_meta(height)
        ts = Timestamp.from_unix_ns(
            meta1.header.time.seconds * 10**9 + meta1.header.time.nanos
        )

        def mk_vote(tag):
            return Vote(
                type=PRECOMMIT_TYPE, height=height, round=0,
                block_id=BlockID(hash=tag * 32,
                                 part_set_header=PartSetHeader(1, tag * 32)),
                timestamp=ts, validator_address=addr, validator_index=0,
            )

        va, vb = mk_vote(b"\xaa"), mk_vote(b"\xbb")
        va.signature = sk.sign(va.sign_bytes("ext-chain"))
        vb.signature = sk.sign(vb.sign_bytes("ext-chain"))
        vals = node.state_store.load_validators(height)
        ev = DuplicateVoteEvidence.from_votes(va, vb, ts, vals)
        raw = b64mod.b64encode(evidence_to_proto(ev).encode()).decode()
        out = rpc.call("broadcast_evidence", evidence=raw)
        assert out["hash"] == ev.hash().hex().upper()

        # genesis_chunked (rpc/core/net.go:131): small genesis = 1 chunk
        # that round-trips to the same doc
        gc = rpc.call("genesis_chunked", chunk=0)
        assert gc["total"] == "1" and gc["chunk"] == "0"
        doc = json.loads(b64mod.b64decode(gc["data"]))
        assert doc["chain_id"] == "ext-chain"
        with pytest.raises(Exception, match="out of range"):
            rpc.call("genesis_chunked", chunk=5)

        # unsafe dial routes are disabled unless rpc.unsafe
        # (rpc/core/routes.go:51-57)
        with pytest.raises(Exception, match="unsafe"):
            rpc.call("dial_seeds", seeds=["aa@127.0.0.1:1"])
        node.config.rpc.unsafe = True
        out = rpc.call("dial_peers", peers=["00" * 20 + "@127.0.0.1:1"])
        assert "Dialing" in out["log"]
        node.config.rpc.unsafe = False
    finally:
        node.stop()


@pytest.mark.slow
def test_cli_reindex_event(tmp_path):
    """commands/reindex_event.go: offline re-index from the stores; the
    rebuilt index serves the same tx and block-event lookups."""
    home = _mk_home(tmp_path, "ri", chain_id="ri-chain")
    cfg = _test_cfg(home)
    cfg.base.db_backend = "sqlite"  # reindex is offline: needs a disk DB
    save_config(cfg)
    node = Node(cfg)
    node.start()
    try:
        rpc = HTTPClient(node.rpc_server.listen_addr)
        res = rpc.broadcast_tx_commit(b"reindex=me")
        txhash = res["hash"]
        assert _wait(
            lambda: int(rpc.status()["sync_info"]["latest_block_height"]) >= 3
        )
    finally:
        node.stop()

    assert cli_main(["--home", home, "reindex-event"]) == 0
    assert (
        cli_main(["--home", home, "reindex-event", "--start-height", "999"]) == 1
    )

    # the rebuilt kv index resolves the committed tx
    from cometbft_tpu.indexer import TxIndexer
    from cometbft_tpu.node import default_db_provider
    from cometbft_tpu.store.db import PrefixDB

    db = default_db_provider(load_config(home))
    try:
        rec = TxIndexer(PrefixDB(db, b"txi/")).get(bytes.fromhex(txhash))
        assert rec is not None
        import base64 as b64mod

        assert b64mod.b64decode(rec["tx"]) == b"reindex=me"
    finally:
        db.close()


def test_mempool_routes_unconfirmed_tx_and_flush():
    """unconfirmed_tx + unsafe_flush_mempool (rpc/core/mempool.go,
    routes.go:63) against a real mempool, no live chain — deterministic."""
    from cometbft_tpu.abci import KVStoreApplication
    from cometbft_tpu.abci.kvstore import default_lanes
    from cometbft_tpu.mempool import CListMempool, MempoolConfig
    from cometbft_tpu.mempool.mempool import key_of
    from cometbft_tpu.proxy import local_client_creator, new_app_conns
    from cometbft_tpu.rpc.core import Environment, RPCError

    conns = new_app_conns(local_client_creator(KVStoreApplication()))
    conns.start()
    try:
        mp = CListMempool(
            MempoolConfig(), conns.mempool,
            lane_priorities=default_lanes(), default_lane="default",
        )
        mp.check_tx(b"pending=1")

        class _Cfg:
            class rpc:
                unsafe = False

        class _Node:
            mempool = mp
            config = _Cfg()

        env = Environment.__new__(Environment)
        env.node = _Node()

        key = key_of(b"pending=1")
        out = env.unconfirmed_tx(hash=key.hex())
        import base64

        assert base64.b64decode(out["tx"]) == b"pending=1"
        with pytest.raises(RPCError, match="not found"):
            env.unconfirmed_tx(hash="ab" * 32)

        # flush is unsafe-gated (AddUnsafeRoutes)
        with pytest.raises(RPCError, match="unsafe"):
            env.unsafe_flush_mempool()
        _Cfg.rpc.unsafe = True
        assert mp.size() == 1
        env.unsafe_flush_mempool()
        assert mp.size() == 0
    finally:
        conns.stop()


def test_config_migrate_reports_and_rewrites(tmp_path):
    """confix-style migration (internal/confix): an old config with a
    missing new key and an obsolete key migrates to the current schema —
    recognized values kept, obsolete keys dropped (with a .bak), new
    keys added at defaults."""
    home = _mk_home(tmp_path, "mig", chain_id="mig-chain")
    cfg_path = os.path.join(home, "config", "config.toml")
    # simulate an older version: drop one current key, add an obsolete
    # one, and keep a customized value
    text = open(cfg_path).read()
    lines = [
        l for l in text.splitlines() if not l.startswith("db_backend")
    ]
    lines.insert(1, 'fast_sync_removed_in_v1 = true')
    lines = [
        'moniker = "migrated-node"' if l.startswith("moniker") else l
        for l in lines
    ]
    open(cfg_path, "w").write("\n".join(lines) + "\n")

    from cometbft_tpu.config import migrate_report

    rep = migrate_report(home)
    assert "db_backend" in rep["added"]
    assert "fast_sync_removed_in_v1" in rep["dropped"]
    assert "moniker" in rep["kept"]

    assert cli_main(["--home", home, "config", "migrate"]) == 0
    assert os.path.exists(cfg_path + ".bak")
    cfg = load_config(home)
    assert cfg.base.moniker == "migrated-node"  # custom value survived
    out = open(cfg_path).read()
    assert "db_backend" in out  # new key materialized
    assert "fast_sync_removed_in_v1" not in out  # obsolete key dropped


def test_config_migrate_renames_carry_values(tmp_path):
    """Cross-version renames (internal/confix/migrations.go per-version
    plans): an old config using pre-rename keys carries its VALUES to the
    new names instead of dropping them — both when migrating and when a
    node simply loads the old file."""
    home = _mk_home(tmp_path, "ren", chain_id="ren-chain")
    cfg_path = os.path.join(home, "config", "config.toml")
    text = open(cfg_path).read()
    lines = [
        l for l in text.splitlines() if not l.startswith("block_sync")
    ]
    # v0.34/v0.36-style spellings: top-level fast_sync + [fastsync] version
    lines.insert(1, "fast_sync = false")
    lines.append("")
    lines.append("[fastsync]")
    lines.append('version = "v0"')
    open(cfg_path, "w").write("\n".join(lines) + "\n")

    from cometbft_tpu.config import migrate_report

    rep = migrate_report(home)
    assert "fast_sync -> block_sync" in rep["renamed"]
    assert "fastsync.version (retired)" in rep["renamed"]
    assert "block_sync" in rep["kept"]

    # a plain load honors the old spelling (value carried, not default)
    assert load_config(home).base.block_sync is False

    assert cli_main(["--home", home, "config", "migrate"]) == 0
    out = open(cfg_path).read()
    assert "block_sync = false" in out
    assert "fast_sync" not in out.replace("block_sync", "")
    assert "[fastsync]" not in out
