"""Consensus state machine tests: single-validator chain (the e2e
vertical slice) and in-process multi-validator networks wired by direct
queue cross-feeding (mirrors reference internal/consensus/state_test.go +
common_test.go topology)."""

import threading
import time

import pytest

from cometbft_tpu.abci import KVStoreApplication
from cometbft_tpu.abci.kvstore import default_lanes
from cometbft_tpu.consensus.config import test_consensus_config
from cometbft_tpu.consensus.state import (
    BlockPartMessage,
    ConsensusState,
    ProposalMessage,
    VoteMessage,
)
from cometbft_tpu.crypto import ed25519
from cometbft_tpu.mempool import CListMempool, MempoolConfig
from cometbft_tpu.privval import FilePV
from cometbft_tpu.proxy import local_client_creator, new_app_conns
from cometbft_tpu.state.execution import BlockExecutor
from cometbft_tpu.state.state import make_genesis_state
from cometbft_tpu.state.store import StateStore
from cometbft_tpu.store.block_store import BlockStore
from cometbft_tpu.store.db import MemDB
from cometbft_tpu.types.event_bus import (
    EventBus,
    EventQueryNewBlock,
)
from cometbft_tpu.types.genesis import GenesisDoc, GenesisValidator
from cometbft_tpu.wire import abci_pb as pb
from cometbft_tpu.wire.canonical import Timestamp

GENESIS_NS = 1_700_000_000 * 1_000_000_000


def make_node(keys, my_key, genesis, wal_path=None):
    """Build one in-process consensus node (common_test.go newState)."""
    state = make_genesis_state(genesis)
    app = KVStoreApplication(lanes=default_lanes())
    conns = new_app_conns(local_client_creator(app))
    conns.start()
    app.init_chain(
        pb.InitChainRequest(
            chain_id=genesis.chain_id,
            validators=[
                pb.ValidatorUpdate(
                    power=10, pub_key_type="ed25519", pub_key_bytes=k.pub_key().data
                )
                for k in keys
            ],
        )
    )
    state_store = StateStore(MemDB())
    state_store.bootstrap(state)
    block_store = BlockStore(MemDB())
    mempool = CListMempool(
        MempoolConfig(),
        conns.mempool,
        lane_priorities=default_lanes(),
        default_lane="default",
    )
    event_bus = EventBus()
    executor = BlockExecutor(
        state_store, conns.consensus, mempool,
        block_store=block_store, event_bus=event_bus,
    )
    cfg = test_consensus_config()
    cfg.wal_path = wal_path or ""
    cs = ConsensusState(
        cfg, state, executor, block_store, mempool, event_bus=event_bus
    )
    cs.set_priv_validator(FilePV(key=_pv_key(my_key), last_sign_state=_pv_state()))
    cs._conns = conns  # keep for teardown
    cs._mempool = mempool
    return cs


def _pv_key(priv):
    from cometbft_tpu.privval.file_pv import FilePVKey

    return FilePVKey(priv)


def _pv_state():
    from cometbft_tpu.privval.file_pv import FilePVLastSignState

    return FilePVLastSignState()


def _genesis(keys, chain_id="cs-chain"):
    return GenesisDoc(
        chain_id=chain_id,
        genesis_time=Timestamp.from_unix_ns(GENESIS_NS),
        validators=[
            GenesisValidator(
                pub_key_type="ed25519", pub_key_bytes=k.pub_key().data, power=10
            )
            for k in keys
        ],
        app_hash=b"\x00" * 8,
    )


def _wait_for_height(cs, height, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cs.state.last_block_height >= height:
            return True
        time.sleep(0.05)
    return False


@pytest.mark.slow
def test_single_validator_produces_blocks(tmp_path):
    """The minimum end-to-end slice (SURVEY §7.5): one self-proposing
    validator runs propose → sign → commit → VerifyCommit → ApplyBlock
    through real consensus timing."""
    key = ed25519.PrivKey.from_seed(b"\x11" * 32)
    cs = make_node([key], key, _genesis([key]), wal_path=str(tmp_path / "wal"))
    sub = cs.event_bus.subscribe("t", EventQueryNewBlock)
    cs._mempool.check_tx(b"probe=1")
    cs.start()
    try:
        assert _wait_for_height(cs, 3), f"stuck at {cs.state.last_block_height}"
        msg, _ = sub.get(timeout=1)
        assert msg.data["block"].header.height == 1
        # block 1 carried the tx
        b1 = cs.block_store.load_block(1)
        assert b"probe=1" in b1.data.txs
        # commits verify: block 2's last_commit signed block 1
        b2 = cs.block_store.load_block(2)
        assert b2.last_commit.block_id.hash == b1.hash()
    finally:
        cs.stop()
        cs._conns.stop()


@pytest.mark.slow
def test_wal_written_and_replayable(tmp_path):
    key = ed25519.PrivKey.from_seed(b"\x12" * 32)
    wal_path = str(tmp_path / "wal")
    cs = make_node([key], key, _genesis([key]), wal_path=wal_path)
    cs.start()
    try:
        assert _wait_for_height(cs, 2)
    finally:
        cs.stop()
        cs._conns.stop()
    # WAL contains EndHeight markers + our signed votes
    from cometbft_tpu.consensus.wal import WAL

    wal = WAL(wal_path)
    kinds = [r.msg.which() for r in wal.iter_records()]
    assert "end_height" in kinds and "msg_info" in kinds
    heights = [
        r.msg.end_height.height for r in wal.iter_records()
        if r.msg.which() == "end_height"
    ]
    assert 1 in heights and 2 in heights


class Net:
    """N validators cross-feeding consensus messages in-process
    (common_test.go in-memory topology)."""

    def __init__(self, n, tmp_path=None):
        self.keys = [ed25519.PrivKey.from_seed(bytes([40 + i]) * 32) for i in range(n)]
        gen = _genesis(self.keys)
        self.nodes = [make_node(self.keys, k, _genesis(self.keys)) for k in self.keys]
        for i, node in enumerate(self.nodes):
            node.broadcast_hook = self._make_hook(i)

    def _make_hook(self, sender_idx):
        def hook(msg):
            for j, other in enumerate(self.nodes):
                if j == sender_idx:
                    continue
                peer = f"node{sender_idx}"
                if isinstance(msg, VoteMessage):
                    other.add_vote(msg.vote, peer)
                elif isinstance(msg, ProposalMessage):
                    other.set_proposal(msg.proposal, peer)
                elif isinstance(msg, BlockPartMessage):
                    other.add_proposal_block_part(msg.height, msg.round, msg.part, peer)
        return hook

    def start(self):
        for node in self.nodes:
            node.start()

    def stop(self):
        for node in self.nodes:
            try:
                node.stop()
            except Exception:
                pass
            node._conns.stop()


@pytest.mark.slow
def test_four_validator_network_commits_blocks():
    net = Net(4)
    net.start()
    try:
        net.nodes[0]._mempool.check_tx(b"hello=world")
        for node in net.nodes:
            assert _wait_for_height(node, 2, timeout=120), (
                f"node stuck at {node.state.last_block_height}"
            )
        # all nodes committed identical blocks
        h1 = {n.block_store.load_block(1).hash() for n in net.nodes}
        assert len(h1) == 1
        h2 = {n.block_store.load_block(2).hash() for n in net.nodes}
        assert len(h2) == 1
        # app hashes agree
        hashes = {n.state.app_hash for n in net.nodes}
        assert len(hashes) == 1
    finally:
        net.stop()


@pytest.mark.slow
def test_network_progresses_without_one_validator():
    """3 of 4 validators (>2/3 power) keep committing; liveness through
    round timeouts when the missing node is the proposer."""
    net = Net(4)
    # node 3 never starts: its votes are absent
    for node in net.nodes[:3]:
        node.start()
    try:
        for node in net.nodes[:3]:
            assert _wait_for_height(node, 2, timeout=180), (
                f"node stuck at {node.state.last_block_height}"
            )
        blocks = [n.block_store.load_block(1).hash() for n in net.nodes[:3]]
        assert len(set(blocks)) == 1
    finally:
        for node in net.nodes[:3]:
            try:
                node.stop()
            except Exception:
                pass
        for node in net.nodes:
            node._conns.stop()


@pytest.mark.slow
def test_restart_continues_chain(tmp_path):
    """Stop at some height, rebuild the whole node from the persisted
    stores + WAL, and verify the chain continues (WAL catchup replay +
    store-backed state restore — reference replay_test.go)."""
    key = ed25519.PrivKey.from_seed(b"\x13" * 32)
    genesis = _genesis([key])
    wal_path = str(tmp_path / "wal")

    state = make_genesis_state(genesis)
    app = KVStoreApplication(lanes=default_lanes())
    conns = new_app_conns(local_client_creator(app))
    conns.start()
    app.init_chain(
        pb.InitChainRequest(
            chain_id=genesis.chain_id,
            validators=[pb.ValidatorUpdate(power=10, pub_key_type="ed25519",
                                           pub_key_bytes=key.pub_key().data)],
        )
    )
    state_db = MemDB()
    block_db = MemDB()
    state_store = StateStore(state_db)
    state_store.bootstrap(state)
    block_store = BlockStore(block_db)

    def build_cs():
        mempool = CListMempool(
            MempoolConfig(), conns.mempool,
            lane_priorities=default_lanes(), default_lane="default",
        )
        bus = EventBus()
        ex = BlockExecutor(state_store, conns.consensus, mempool,
                           block_store=BlockStore(block_db), event_bus=bus)
        cfg = test_consensus_config()
        cfg.wal_path = wal_path
        cur = state_store.load() or state
        cs = ConsensusState(cfg, cur, ex, BlockStore(block_db), mempool, event_bus=bus)
        from cometbft_tpu.privval.file_pv import FilePVKey, FilePVLastSignState
        cs.set_priv_validator(FilePV(
            key=FilePVKey(key),
            last_sign_state=FilePVLastSignState.load(str(tmp_path / "pvstate.json"))
        ))
        cs.priv_validator.last_sign_state.file_path = str(tmp_path / "pvstate.json")
        return cs

    cs1 = build_cs()
    cs1.start()
    assert _wait_for_height(cs1, 2)
    h_stop = cs1.state.last_block_height
    cs1.stop()

    cs2 = build_cs()
    cs2.start()
    try:
        assert _wait_for_height(cs2, h_stop + 2), (
            f"restarted node stuck at {cs2.state.last_block_height}"
        )
        # chain is continuous across the restart
        for h in range(1, cs2.state.last_block_height):
            b = cs2.block_store.load_block(h + 1)
            prev = cs2.block_store.load_block(h)
            if b is not None and prev is not None and b.last_commit is not None:
                assert b.last_commit.block_id.hash == prev.hash()
    finally:
        cs2.stop()
        conns.stop()


def test_ticker_schedule_if_idle_never_replaces_pending():
    """schedule_if_idle (the watchdog's re-kick path) must decline when a
    legitimate timeout is already armed — an unconditional replace would
    cancel the real timer with a stale (H,R,S) one that _handle_timeout
    then drops (the evaporating-timeout class the watchdog exists to
    catch, not cause)."""
    from cometbft_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker

    fired = []
    t = TimeoutTicker(fired.append)
    real = TimeoutInfo(0.05, height=5, round=1, step=4)
    t.schedule(real)
    # watchdog re-kick while the real timer is pending: declined
    assert t.schedule_if_idle(TimeoutInfo(0.01, 5, 0, 1)) is False
    time.sleep(0.3)
    assert fired == [real]  # the real timeout survived and fired
    # now idle: the re-kick arms
    assert t.schedule_if_idle(TimeoutInfo(0.01, 5, 1, 4)) is True
    time.sleep(0.2)
    assert len(fired) == 2
    # stopped ticker declines everything
    t.stop()
    assert t.schedule_if_idle(TimeoutInfo(0.0, 5, 1, 4)) is False


def test_ticker_post_fire_skips_stale_reschedule():
    """Reference timeoutRoutine keeps the fired TimeoutInfo as the
    shouldSkipTick comparison point: after (H,R,S) fires, a schedule()
    for the SAME or an OLDER (H,R,S) is a stale tick from before the
    state machine advanced and must not re-arm; a genuinely newer one
    must.  (The watchdog's schedule_if_idle path deliberately bypasses
    this — covered above.)"""
    from cometbft_tpu.consensus.ticker import TimeoutInfo, TimeoutTicker

    fired = []
    t = TimeoutTicker(fired.append)
    ti = TimeoutInfo(0.02, height=7, round=2, step=4)
    t.schedule(ti)
    time.sleep(0.2)
    assert fired == [ti]
    # duplicate of the fired timeout: skipped (would re-deliver a tick
    # the machine already consumed)
    t.schedule(TimeoutInfo(0.01, 7, 2, 4))
    # older round: skipped
    t.schedule(TimeoutInfo(0.01, 7, 1, 6))
    time.sleep(0.15)
    assert fired == [ti]
    # a NEWER step after the fire arms normally
    nxt = TimeoutInfo(0.02, height=7, round=2, step=6)
    t.schedule(nxt)
    time.sleep(0.2)
    assert fired == [ti, nxt]
    # the watchdog path may still re-arm the exact fired (H,R,S)
    assert t.schedule_if_idle(TimeoutInfo(0.01, 7, 2, 6)) is True
    time.sleep(0.15)
    assert len(fired) == 3
    t.stop()
