"""GLV endomorphism decomposition pins (ops/secp256k1).

Fast tier works the HOST half of the split (pure bigint — free): the
lattice-basis identities, the half-width bound, and k = k1 + λ·k2 over
adversarial scalars.  The device half is pinned two ways: the traced
jaxpr of the device split against the host split (make_jaxpr runs in
milliseconds, no compile), and — in the slow tier, where the witness
programs' compiles belong — full-batch GLV-vs-Shamir-witness verdict
bit-identity over the adversarial corpus.
"""

import numpy as np
import pytest

from cometbft_tpu.crypto import secp256k1 as host_secp
from cometbft_tpu.ops import secp256k1 as dev

P, N, G = host_secp.P, host_secp.N, host_secp.G


def test_glv_constants_are_the_endomorphism():
    # beta is a nontrivial cube root of 1 mod p, lambda mod n, and they
    # pair: lambda * (x, y) == (beta * x, y) for every curve point
    assert pow(dev._BETA, 3, P) == 1 and dev._BETA != 1
    assert pow(dev._LAM, 3, N) == 1 and dev._LAM != 1
    got = host_secp._mul(dev._LAM, G)
    assert got == (dev._BETA * G[0] % P, G[1])
    # and not just on G: an unrelated point
    Q = host_secp._mul(0xDEADBEEF, G)
    assert host_secp._mul(dev._LAM, Q) == (dev._BETA * Q[0] % P, Q[1])


def test_glv_lattice_basis_identities():
    a1, b1, a2, b2 = dev._A1, dev._B1, dev._A2, dev._B2
    assert abs(a1 * b2 - a2 * b1) == N
    assert (a1 + b1 * dev._LAM) % N == 0
    assert (a2 + b2 * dev._LAM) % N == 0
    # basis vectors are genuinely half-width
    for c in (a1, b1, a2, b2):
        assert abs(c) < 1 << 129


def test_host_split_reconstructs_and_bounds():
    samples = [0, 1, 2, N - 1, N - 2, N // 2, dev._LAM, N - dev._LAM,
               dev._BETA % N, (1 << 255) % N]
    x = 7
    for _ in range(500):
        x = x * x * 1103515245 % N
        samples.append(x)
    for k in samples:
        s1, s2 = dev._split_host(k)
        assert (s1 + dev._LAM * s2) % N == k % N, k
        assert abs(s1) < 1 << 130 and abs(s2) < 1 << 130, k


def test_device_split_matches_host_split_traced():
    """The jitted _glv_split, evaluated eagerly on CPU (no jit, no
    compile): |k1|, |k2| limbs + negation flags must equal the host
    split exactly — the device walk consumes exactly these."""
    samples = [0, 1, N - 1, dev._LAM, N // 3, (1 << 200) % N]
    rng = np.random.default_rng(16)
    samples += [int.from_bytes(rng.bytes(32), "big") % N for _ in range(10)]
    k = np.stack([dev._int_to_limbs(s) for s in samples]).astype(np.int32)
    import jax.numpy as jnp

    k1, n1, k2, n2 = dev._glv_split(jnp.asarray(k))
    for i, s in enumerate(samples):
        h1, h2 = dev._split_host(s)
        assert dev.from_limbs(np.asarray(k1[i])) == abs(h1), s
        assert dev.from_limbs(np.asarray(k2[i])) == abs(h2), s
        assert bool(n1[i]) == (h1 < 0), s
        assert bool(n2[i]) == (h2 < 0), s


def test_sign_bound_splits_negatives_correctly():
    # a scalar just above the sign boundary must come back negative
    for k in range(3):
        s1, s2 = dev._split_host(N - 1 - k)
        assert s1 <= 0 or s1 < dev._GLV_SIGN_BOUND


# ------------------------------------------------------------ slow tier


def _rec_corpus():
    """The PR-15 adversarial builder extended with ecrecover rows —
    every invalid class, poison rows before AND after victims, all
    three wire shapes in one dispatch."""
    from cometbft_tpu.crypto import secp256k1eth as heth
    from tests.test_secp_ops import _corpus as base

    items = base()
    rpk = heth.RecoverPrivKey.from_seed(b"glv-rec")
    addr = rpk.pub_key().data
    msg = b"rec ok"
    items.append((addr, msg, rpk.sign(msg)))
    # tampered sig, wrong address, high-s + flipped v, r >= n, non-QR r
    sig = bytearray(rpk.sign(b"rec t1"))
    sig[3] ^= 1
    items.append((addr, b"rec t1", bytes(sig)))
    items.append((b"\x42" * 20, b"rec t2", rpk.sign(b"rec t2")))
    s0 = rpk.sign(b"rec t3")
    r_ = int.from_bytes(s0[:32], "big")
    s_ = int.from_bytes(s0[32:64], "big")
    items.append((addr, b"rec t3",
                  r_.to_bytes(32, "big") + (N - s_).to_bytes(32, "big")
                  + bytes([s0[64] ^ 1])))
    items.append((addr, b"rec t4",
                  (N + 1).to_bytes(32, "big") + s0[32:64] + b"\x00"))
    x = 5
    while True:
        y2 = (pow(x, 3, P) + host_secp.B) % P
        if pow(y2, (P + 1) // 4, P) ** 2 % P != y2:
            break
        x += 1
    items.append((addr, b"rec t5",
                  x.to_bytes(32, "big") + s0[32:64] + b"\x00"))
    # a second valid rec row AFTER the poison, same 64-bucket
    items.append((addr, b"rec ok 2", rpk.sign(b"rec ok 2")))
    return items


def _witness_pin(items, hash_min):
    import os

    from cometbft_tpu.models import secp_verifier as sv

    want = [sv._host_verify_one(p, m, s) for (p, m, s) in items]
    assert True in want and False in want
    os.environ["COMETBFT_TPU_SECP_HASH_DEVICE_MIN"] = hash_min
    try:
        os.environ["COMETBFT_TPU_SECP_GLV"] = "1"
        _, glv = sv._verify_items(items, use_device=True)
        os.environ["COMETBFT_TPU_SECP_GLV"] = "0"
        _, wit = sv._verify_items(items, use_device=True)
    finally:
        os.environ.pop("COMETBFT_TPU_SECP_GLV", None)
        os.environ.pop("COMETBFT_TPU_SECP_HASH_DEVICE_MIN", None)
    assert glv == wit == want


@pytest.mark.slow
def test_glv_bit_identical_to_shamir_witness_device():
    """THE witness pin: the GLV program and the non-GLV Shamir program
    produce bit-identical verdicts — equal to the host gauntlet — over
    the rec-extended adversarial corpus (all three wire shapes, every
    invalid class, poison rows both sides of victims) in one dispatch;
    the COMB_TREE witness pattern."""
    _witness_pin(_rec_corpus(), hash_min="0")


@pytest.mark.slow
def test_glv_bit_identical_fused_hash_program():
    """Same witness pin through the fused hash->verify dispatch (the
    on-device SHA-256/Keccak-256 digests feed the same verdicts)."""
    _witness_pin(_rec_corpus(), hash_min="1")
