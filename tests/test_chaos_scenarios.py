"""Chaos scenario harness (e2e/scenarios.py + scripts/chaos.py).

Tier-1 runs the single-node ``wedge_smoke`` (the whole failover plane —
trip, degraded-mode liveness, forensics, probation restore — against a
real node process, ~15-40 s) plus the driver's contract on stub
scenarios.  The five multi-node scenarios run in the slow tier, one test
each so a failure isolates."""

import importlib.util
import json
import os

import pytest

from cometbft_tpu.e2e import scenarios as sc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_chaos_mod():
    spec = importlib.util.spec_from_file_location(
        "chaos_driver", os.path.join(REPO, "scripts", "chaos.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ fast tier


def test_chaos_smoke_wedge_single_node(tmp_path):
    """The tier-1 smoke: a real single-node net wedges, trips to CPU
    fallback, keeps committing, emits forensics + the flightrec event,
    and restores TPU mode after the heal."""
    res = sc.run_scenario("wedge_smoke", str(tmp_path), base_port=25500)
    assert res.ok, json.dumps(res.to_dict(), indent=1)
    assert res.liveness and res.safety
    assert res.details.get("tripped") and res.details.get("restored")
    assert res.details.get("forensics_artifact")
    # the per-node artifact bundle landed (diagnosability contract)
    arts = res.details.get("artifacts", {})
    assert arts and all(os.path.exists(p) for p in arts.values())


def test_chaos_driver_json_artifact(tmp_path, monkeypatch, capsys):
    """scripts/chaos.py --json emits one machine-readable verdict per
    scenario and exits non-zero iff any failed (driver contract, proven
    on stub scenarios so it stays fast)."""
    mod = _load_chaos_mod()

    def fake_pass(out_dir, base_port=0):
        return sc.ScenarioResult("fake_pass", ok=True, liveness=True, safety=True)

    def fake_fail(out_dir, base_port=0):
        return sc.ScenarioResult("fake_fail", problems=["injected failure"])

    monkeypatch.setitem(sc.SCENARIOS, "fake_pass", fake_pass)
    monkeypatch.setitem(sc.SCENARIOS, "fake_fail", fake_fail)

    out = tmp_path / "verdict.json"
    rc = mod.main([
        "--scenario", "fake_pass", "--json", str(out),
        "--out", str(tmp_path / "art"),
    ])
    assert rc == 0
    verdict = json.loads(out.read_text())
    assert verdict["ok"] is True
    assert [s["name"] for s in verdict["scenarios"]] == ["fake_pass"]
    assert {"name", "ok", "liveness", "safety", "problems", "details",
            "artifact_dir", "elapsed_s"} <= set(verdict["scenarios"][0])
    # stdout carried one JSON line per scenario (the streaming artifact)
    lines = [
        json.loads(ln) for ln in capsys.readouterr().out.splitlines()
        if ln.startswith("{")
    ]
    assert [ln["name"] for ln in lines] == ["fake_pass"]

    rc = mod.main([
        "--scenario", "fake_pass", "--scenario", "fake_fail",
        "--json", str(out), "--out", str(tmp_path / "art2"),
    ])
    assert rc == 1
    verdict = json.loads(out.read_text())
    assert verdict["ok"] is False
    assert [s["ok"] for s in verdict["scenarios"]] == [True, False]


def test_chaos_driver_rejects_unknown_scenario(tmp_path):
    mod = _load_chaos_mod()
    assert mod.main(["--scenario", "nope"]) == 2
    assert mod.main(["--scenario", "wedge_smoke", "--repeat", "0"]) == 2
    with pytest.raises(ValueError, match="unknown scenario"):
        sc.run_scenario("nope", str(tmp_path))


def test_chaos_driver_repeat_and_seed(tmp_path, monkeypatch):
    """--repeat N re-runs the scenario list with per-iteration port
    offsets (no collisions) and --seed pins the deterministic load-round
    base — the shape the soak uses for mid-run fault injections."""
    mod = _load_chaos_mod()
    calls = []

    def fake_pass(out_dir, base_port=0):
        calls.append((out_dir, base_port))
        return sc.ScenarioResult(
            "fake_pass", ok=True, liveness=True, safety=True
        )

    monkeypatch.setitem(sc.SCENARIOS, "fake_pass", fake_pass)
    out = tmp_path / "verdict.json"
    rc = mod.main([
        "--scenario", "fake_pass", "--repeat", "3", "--seed", "42",
        "--json", str(out), "--out", str(tmp_path / "art"),
        "--base-port", "31000",
    ])
    assert rc == 0
    verdict = json.loads(out.read_text())
    assert verdict["repeat"] == 3 and verdict["seed"] == 42
    assert [s["name"] for s in verdict["scenarios"]] == ["fake_pass"] * 3
    assert [s["details"]["repeat"] for s in verdict["scenarios"]] == [0, 1, 2]
    # per-iteration base ports never collide; per-iteration artifact dirs
    ports = [p for (_d, p) in calls]
    assert len(set(ports)) == 3
    dirs = [d for (d, _p) in calls]
    assert len(set(dirs)) == 3
    # the seed pinned the scenarios' deterministic round numbering
    assert sc._SEED == 42
    assert sc._round_id_base() == (42 * 1009) % 100000
    # an unseeded run resets to time-derived rounds
    rc = mod.main([
        "--scenario", "fake_pass", "--json", str(out),
        "--out", str(tmp_path / "art2"),
    ])
    assert rc == 0 and sc._SEED is None


def test_chaos_driver_crash_exits_3_not_1(tmp_path, monkeypatch):
    """A scenario that RAISES (harness breakage) exits 3 and is marked
    crashed in the verdict — distinct from an assertion failure's 1."""
    mod = _load_chaos_mod()

    def fake_crash(out_dir, base_port=0):
        raise RuntimeError("harness exploded")

    def fake_fail(out_dir, base_port=0):
        return sc.ScenarioResult("fake_fail", problems=["assertion failed"])

    monkeypatch.setitem(sc.SCENARIOS, "fake_crash", fake_crash)
    monkeypatch.setitem(sc.SCENARIOS, "fake_fail", fake_fail)
    out = tmp_path / "verdict.json"
    rc = mod.main([
        "--scenario", "fake_crash", "--json", str(out),
        "--out", str(tmp_path / "a"),
    ])
    assert rc == 3
    verdict = json.loads(out.read_text())
    assert verdict["crashed"] is True
    assert verdict["scenarios"][0]["crashed"] is True
    assert "traceback" in verdict["scenarios"][0]["details"]

    # plain failure still exits 1; a crash anywhere in the list wins
    rc = mod.main([
        "--scenario", "fake_fail", "--json", str(out),
        "--out", str(tmp_path / "b"),
    ])
    assert rc == 1
    assert json.loads(out.read_text())["crashed"] is False
    rc = mod.main([
        "--scenario", "fake_fail", "--scenario", "fake_crash",
        "--json", str(out), "--out", str(tmp_path / "c"),
    ])
    assert rc == 3


def test_registry_names_the_six_full_scenarios():
    assert set(sc.DEFAULT_SCENARIOS) == {
        "wedge", "crash_replay", "partition_heal", "double_sign",
        "valset_rotation_blocksync", "plane_crash",
    }
    # the two smokes ride in the registry but not the default chaos run
    assert set(sc.DEFAULT_SCENARIOS) | {"wedge_smoke", "trace_smoke"} == set(
        sc.SCENARIOS
    )


# ------------------------------------------------------------ slow tier


@pytest.mark.slow
def test_scenario_wedge(tmp_path):
    res = sc.run_scenario("wedge", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)


@pytest.mark.slow
def test_scenario_crash_replay(tmp_path):
    res = sc.run_scenario("crash_replay", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)


@pytest.mark.slow
def test_scenario_partition_heal(tmp_path):
    res = sc.run_scenario("partition_heal", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)


@pytest.mark.slow
def test_scenario_double_sign(tmp_path):
    res = sc.run_scenario("double_sign", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)


@pytest.mark.slow
def test_scenario_valset_rotation_blocksync(tmp_path):
    res = sc.run_scenario("valset_rotation_blocksync", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)


@pytest.mark.slow
def test_scenario_plane_crash(tmp_path):
    """3 real node processes on one shared verifyd; kill -9 it
    mid-height, liveness resumes via every node's breaker fallback, the
    restarted plane probation-restores and serves again (the fast
    single-process twin is tests/test_verifyrpc.py's loopback smoke)."""
    res = sc.run_scenario("plane_crash", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)
    d = res.details
    assert d["plane_requests_before_crash"] > 0
    assert d["breakers_after_crash"] == ["open"] * 3
    assert d["breakers_after_restart"] == ["closed"] * 3
    assert d["plane_requests_after_restart"] > 0


@pytest.mark.slow
def test_scenario_trace_smoke(tmp_path):
    """The PR-17 acceptance run: node + real verifyd subprocess with
    tracing armed in both; after clean SIGTERM exits the merged Perfetto
    timeline spans both processes with a consensus-side span sharing a
    trace_id with the plane's server-side verify.rpc.serve span, and
    /height_timeline reported phase wall times for >= 5 heights."""
    res = sc.run_scenario("trace_smoke", str(tmp_path))
    assert res.ok, json.dumps(res.to_dict(), indent=1)
    d = res.details
    assert d["timeline_heights"] >= 5
    assert d["trace_pids"] >= 2
    assert d["linked_trace_ids"] >= 1
    # the merged doc itself is Perfetto-loadable trace-event JSON
    with open(d["merged_trace"]) as f:
        doc = json.load(f)
    assert isinstance(doc["traceEvents"], list) and doc["traceEvents"]
