"""BLS12-381 key type (reference: crypto/bls12381/key_bls12381.go).

Pairing correctness is checked structurally (bilinearity, negative
controls) since the implementation is self-contained; serialization is
pinned against the universally-known ZCash-format compressed
generators.
"""

import pytest

from cometbft_tpu.crypto import bls12381 as bls

# The compressed generators are fixed, publicly-known constants — any
# BLS12-381 library prints these exact bytes.
G1_GEN_COMPRESSED = (
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac586c"
    "55e83ff97a1aeffb3af00adb22c6bb"
)
G2_GEN_COMPRESSED = (
    "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f504933"
    "4cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051c6e4"
    "7ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8"
)


def test_generator_serialization_pinned():
    assert bls._g1_compress(bls.G1_GEN).hex() == G1_GEN_COMPRESSED
    assert bls._g2_compress(bls.G2_GEN).hex() == G2_GEN_COMPRESSED
    assert bls._g1_decompress(bytes.fromhex(G1_GEN_COMPRESSED)) == bls.G1_GEN
    assert bls._g2_decompress(bytes.fromhex(G2_GEN_COMPRESSED)) == bls.G2_GEN


def test_subgroup_and_curve_checks():
    assert bls._on_curve(bls._FP, bls.G1_GEN)
    assert bls._on_curve(bls._FP2, bls.G2_GEN)
    assert bls._in_subgroup(bls._FP, bls.G1_GEN)
    assert bls._in_subgroup(bls._FP2, bls.G2_GEN)
    # r * G = infinity exactly
    assert bls._jac_mul(bls._FP, bls._from_affine(bls._FP, bls.G1_GEN), bls.R)[2] == 0


def test_infinity_pubkey_rejected():
    inf = bytes([0xC0]) + bytes(47)
    with pytest.raises(ValueError, match="infinite"):
        bls.PubKey(inf)


def test_malformed_points_rejected():
    with pytest.raises(ValueError):
        bls._g1_decompress(bytes(48))  # no compression flag
    bad_x = bytearray(bytes.fromhex(G1_GEN_COMPRESSED))
    bad_x[-1] ^= 1
    # flipping x usually leaves the curve; accept either not-on-curve or
    # a different valid point — but never the generator
    try:
        pt = bls._g1_decompress(bytes(bad_x))
        assert pt != bls.G1_GEN
    except ValueError:
        pass


def test_sign_verify_and_tamper():
    sk = bls.PrivKey.from_secret(b"validator-1")
    pk = sk.pub_key()
    assert len(pk.data) == bls.PUBKEY_SIZE
    assert len(pk.address()) == 20
    msg = b"precommit|height=5|round=0"
    sig = sk.sign(msg)
    assert len(sig) == bls.SIG_SIZE
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"!", sig)
    assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    # signature by a different key
    sk2 = bls.PrivKey.from_secret(b"validator-2")
    assert not sk2.pub_key().verify_signature(msg, sig)


def test_deterministic_keygen():
    a = bls.PrivKey.from_secret(b"seed")
    b = bls.PrivKey.from_secret(b"seed")
    assert a.bytes() == b.bytes()
    assert a.pub_key().data == b.pub_key().data
    assert bls.PrivKey.from_secret(b"other").bytes() != a.bytes()


@pytest.mark.slow
def test_aggregate_verify_distinct_messages():
    sks = [bls.PrivKey.from_secret(b"agg-%d" % i) for i in range(3)]
    pks = [sk.pub_key() for sk in sks]
    msgs = [b"vote-%d" % i for i in range(3)]
    agg = bls.aggregate_signatures([sk.sign(m) for sk, m in zip(sks, msgs)])
    assert len(agg) == bls.SIG_SIZE
    assert bls.aggregate_verify(pks, msgs, agg)
    # swap two messages: must fail
    assert not bls.aggregate_verify(pks, [msgs[1], msgs[0], msgs[2]], agg)


@pytest.mark.slow
def test_fast_aggregate_verify_same_message():
    sks = [bls.PrivKey.from_secret(b"fagg-%d" % i) for i in range(4)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"commit|height=9"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert bls.fast_aggregate_verify(pks, msg, agg)
    # missing one signer
    partial = bls.aggregate_signatures([sk.sign(msg) for sk in sks[:3]])
    assert not bls.fast_aggregate_verify(pks, msg, partial)


def test_proto_roundtrip():
    from cometbft_tpu.crypto import encoding

    pk = bls.PrivKey.from_secret(b"proto").pub_key()
    back = encoding.pubkey_from_proto(encoding.pubkey_to_proto(pk))
    assert isinstance(back, bls.PubKey) and back.data == pk.data


@pytest.mark.slow
def test_aggregate_verify_rejects_duplicate_messages():
    """Basic (NUL) scheme: duplicate messages reopen the rogue-key attack,
    so AggregateVerify must reject them outright."""
    sks = [bls.PrivKey.from_secret(b"dup-%d" % i) for i in range(2)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"same-message"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert not bls.aggregate_verify(pks, [msg, msg], agg)


@pytest.mark.slow
def test_proof_of_possession():
    sk = bls.PrivKey.from_secret(b"pop-1")
    pk = sk.pub_key()
    proof = bls.pop_prove(sk)
    assert bls.pop_verify(pk, proof)
    # a PoP for a different key does not transfer
    other = bls.PrivKey.from_secret(b"pop-2").pub_key()
    assert not bls.pop_verify(other, proof)
    # an ordinary signature over pk bytes is NOT a PoP (different DST)
    assert not bls.pop_verify(pk, sk.sign(pk.data))
