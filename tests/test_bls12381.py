"""BLS12-381 key type (reference: crypto/bls12381/key_bls12381.go).

Pairing correctness is checked structurally (bilinearity, negative
controls) since the implementation is self-contained; serialization is
pinned against the universally-known ZCash-format compressed
generators.
"""

import pytest

from cometbft_tpu.crypto import bls12381 as bls

# The compressed generators are fixed, publicly-known constants — any
# BLS12-381 library prints these exact bytes.
G1_GEN_COMPRESSED = (
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac586c"
    "55e83ff97a1aeffb3af00adb22c6bb"
)
G2_GEN_COMPRESSED = (
    "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f504933"
    "4cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051c6e4"
    "7ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8"
)


def test_generator_serialization_pinned():
    assert bls._g1_compress(bls.G1_GEN).hex() == G1_GEN_COMPRESSED
    assert bls._g2_compress(bls.G2_GEN).hex() == G2_GEN_COMPRESSED
    assert bls._g1_decompress(bytes.fromhex(G1_GEN_COMPRESSED)) == bls.G1_GEN
    assert bls._g2_decompress(bytes.fromhex(G2_GEN_COMPRESSED)) == bls.G2_GEN


def test_subgroup_and_curve_checks():
    assert bls._on_curve(bls._FP, bls.G1_GEN)
    assert bls._on_curve(bls._FP2, bls.G2_GEN)
    assert bls._in_subgroup(bls._FP, bls.G1_GEN)
    assert bls._in_subgroup(bls._FP2, bls.G2_GEN)
    # r * G = infinity exactly
    assert bls._jac_mul(bls._FP, bls._from_affine(bls._FP, bls.G1_GEN), bls.R)[2] == 0


def test_infinity_pubkey_rejected():
    inf = bytes([0xC0]) + bytes(47)
    with pytest.raises(ValueError, match="infinite"):
        bls.PubKey(inf)


def test_malformed_points_rejected():
    with pytest.raises(ValueError):
        bls._g1_decompress(bytes(48))  # no compression flag
    bad_x = bytearray(bytes.fromhex(G1_GEN_COMPRESSED))
    bad_x[-1] ^= 1
    # flipping x usually leaves the curve; accept either not-on-curve or
    # a different valid point — but never the generator
    try:
        pt = bls._g1_decompress(bytes(bad_x))
        assert pt != bls.G1_GEN
    except ValueError:
        pass


def test_sign_verify_and_tamper():
    sk = bls.PrivKey.from_secret(b"validator-1")
    pk = sk.pub_key()
    assert len(pk.data) == bls.PUBKEY_SIZE
    assert len(pk.address()) == 20
    msg = b"precommit|height=5|round=0"
    sig = sk.sign(msg)
    assert len(sig) == bls.SIG_SIZE
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"!", sig)
    assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    # signature by a different key
    sk2 = bls.PrivKey.from_secret(b"validator-2")
    assert not sk2.pub_key().verify_signature(msg, sig)


def test_deterministic_keygen():
    a = bls.PrivKey.from_secret(b"seed")
    b = bls.PrivKey.from_secret(b"seed")
    assert a.bytes() == b.bytes()
    assert a.pub_key().data == b.pub_key().data
    assert bls.PrivKey.from_secret(b"other").bytes() != a.bytes()


@pytest.mark.slow
def test_aggregate_verify_distinct_messages():
    sks = [bls.PrivKey.from_secret(b"agg-%d" % i) for i in range(3)]
    pks = [sk.pub_key() for sk in sks]
    msgs = [b"vote-%d" % i for i in range(3)]
    agg = bls.aggregate_signatures([sk.sign(m) for sk, m in zip(sks, msgs)])
    assert len(agg) == bls.SIG_SIZE
    assert bls.aggregate_verify(pks, msgs, agg)
    # swap two messages: must fail
    assert not bls.aggregate_verify(pks, [msgs[1], msgs[0], msgs[2]], agg)


@pytest.mark.slow
def test_fast_aggregate_verify_same_message():
    sks = [bls.PrivKey.from_secret(b"fagg-%d" % i) for i in range(4)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"commit|height=9"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert bls.fast_aggregate_verify(pks, msg, agg)
    # missing one signer
    partial = bls.aggregate_signatures([sk.sign(msg) for sk in sks[:3]])
    assert not bls.fast_aggregate_verify(pks, msg, partial)


def test_proto_roundtrip():
    from cometbft_tpu.crypto import encoding

    pk = bls.PrivKey.from_secret(b"proto").pub_key()
    back = encoding.pubkey_from_proto(encoding.pubkey_to_proto(pk))
    assert isinstance(back, bls.PubKey) and back.data == pk.data


@pytest.mark.slow
def test_aggregate_verify_rejects_duplicate_messages():
    """Basic (NUL) scheme: duplicate messages reopen the rogue-key attack,
    so AggregateVerify must reject them outright."""
    sks = [bls.PrivKey.from_secret(b"dup-%d" % i) for i in range(2)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"same-message"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert not bls.aggregate_verify(pks, [msg, msg], agg)


@pytest.mark.slow
def test_proof_of_possession():
    sk = bls.PrivKey.from_secret(b"pop-1")
    pk = sk.pub_key()
    proof = bls.pop_prove(sk)
    assert bls.pop_verify(pk, proof)
    # a PoP for a different key does not transfer
    other = bls.PrivKey.from_secret(b"pop-2").pub_key()
    assert not bls.pop_verify(other, proof)
    # an ordinary signature over pk bytes is NOT a PoP (different DST)
    assert not bls.pop_verify(pk, sk.sign(pk.data))


def test_sswu_map_structure():
    """SSWU must land on E' (y² = x³ + A'x + B'), the 3-isogeny must land
    on E, and u = 0 (the tv1 == 0 exceptional case) must not crash."""
    import random

    from cometbft_tpu.crypto import bls12381 as B

    def on_eprime(pt):
        x, y = pt
        rhs = B.f2_add(
            B.f2_add(B.f2_mul(B.f2_sqr(x), x), B.f2_mul(B._SSWU_A, x)),
            B._SSWU_B,
        )
        return B.f2_sqr(y) == rhs

    rnd = random.Random(5)
    us = [(0, 0)] + [(rnd.randrange(B.P), rnd.randrange(B.P)) for _ in range(4)]
    for u in us:
        q = B._map_to_curve_sswu_g2(u)
        assert on_eprime(q), f"SSWU output off E' for u={u}"
        p = B._iso3_map(q)
        assert p is not None and B._on_curve(B._FP2, p), "isogeny output off E"


def test_hash_to_g2_rfc9380_vectors():
    """Wire-compatibility pin: RFC 9380 Appendix J.10.1 test vectors for
    BLS12381G2_XMD:SHA-256_SSWU_RO_ (values transcribed from the RFC —
    the correct use of public conformance data).  Passing these means
    signatures interoperate with blst, which the reference binds
    (crypto/bls12381/key_bls12381.go:30-41)."""
    from cometbft_tpu.crypto import bls12381 as B

    dst = b"QUUX-V01-CS02-with-BLS12381G2_XMD:SHA-256_SSWU_RO_"
    vectors = {
        b"": (
            (0x0141EBFBDCA40EB85B87142E130AB689C673CF60F1A3E98D69335266F30D9B8D4AC44C1038E9DCDD5393FAF5C41FB78A,
             0x05CB8437535E20ECFFAEF7752BADDF98034139C38452458BAEEFAB379BA13DFF5BF5DD71B72418717047F5B0F37DA03D),
            (0x0503921D7F6A12805E72940B963C0CF3471C7B2A524950CA195D11062EE75EC076DAF2D4BC358C4B190C0C98064FDD92,
             0x12424AC32561493F3FE3C260708A12B7C620E7BE00099A974E259DDC7D1F6395C3C811CDD19F1E8DBF3E9ECFDCBAB8D6),
        ),
        b"abc": (
            (0x02C2D18E033B960562AAE3CAB37A27CE00D80CCD5BA4B7FE0E7A210245129DBEC7780CCC7954725F4168AFF2787776E6,
             0x139CDDBCCDC5E91B9623EFD38C49F81A6F83F175E80B06FC374DE9EB4B41DFE4CA3A230ED250FBE3A2ACF73A41177FD8),
            (0x1787327B68159716A37440985269CF584BCB1E621D3A7202BE6EA05C4CFE244AEB197642555A0645FB87BF7466B2BA48,
             0x00AA65DAE3C8D732D10ECD2C50F8A1BAF3001578F71C694E03866E9F3D49AC1E1CE70DD94A733534F106D4CEC0EDDD16),
        ),
        b"abcdef0123456789": (
            (0x121982811D2491FDE9BA7ED31EF9CA474F0E1501297F68C298E9F4C0028ADD35AEA8BB83D53C08CFC007C1E005723CD0,
             0x190D119345B94FBD15497BCBA94ECF7DB2CBFD1E1FE7DA034D26CBBA169FB3968288B3FAFB265F9EBD380512A71C3F2C),
            (0x05571A0F8D3C08D094576981F4A3B8EDA0A8E771FCDCC8ECCEAF1356A6ACF17574518ACB506E435B639353C2E14827C8,
             0x0BB5E7572275C567462D91807DE765611490205A941A5A6AF3B1691BFE596C31225D3AABDF15FAFF860CB4EF17C7C3BE),
        ),
    }
    for msg, (want_x, want_y) in vectors.items():
        x, y = B.hash_to_g2(msg, dst)
        assert x == want_x, f"x mismatch for {msg!r}"
        assert y == want_y, f"y mismatch for {msg!r}"


def test_native_pairing_core_matches_python():
    """native/bls381.cc must agree with the pure-Python pairing: full
    pairing values coefficient-by-coefficient, and the product check on
    both a valid signature relation and a broken one."""
    import ctypes
    import random

    from cometbft_tpu.crypto import bls12381 as B

    lib = B._native_pairing_lib()
    if lib is None:
        import pytest

        pytest.skip("native pairing core unavailable")
    lib.bls381_pairing.restype = None

    rnd = random.Random(7)
    for _ in range(2):
        k1 = rnd.randrange(1, B.R)
        k2 = rnd.randrange(1, B.R)
        p = B._to_affine(B._FP, B._jac_mul(B._FP, B._from_affine(B._FP, B.G1_GEN), k1))
        q = B._to_affine(
            B._FP2, B._jac_mul(B._FP2, B._from_affine(B._FP2, B.G2_GEN), k2)
        )
        want = B._final_exp(B._miller(q, p))
        a1 = (ctypes.c_uint64 * 12)(*(B._limbs6(p[0]) + B._limbs6(p[1])))
        a2 = (ctypes.c_uint64 * 24)(
            *(B._limbs6(q[0][0]) + B._limbs6(q[0][1])
              + B._limbs6(q[1][0]) + B._limbs6(q[1][1]))
        )
        out = (ctypes.c_uint64 * 72)()
        lib.bls381_pairing(a1, a2, out)
        got = tuple(
            (
                sum(out[i * 12 + j] << (64 * j) for j in range(6)),
                sum(out[i * 12 + 6 + j] << (64 * j) for j in range(6)),
            )
            for i in range(6)
        )
        assert got == want

    # bilinearity through the product check: e(-kP, Q) * e(P, kQ) == 1
    k = rnd.randrange(2, B.R)
    kp = B._to_affine(B._FP, B._jac_mul(B._FP, B._from_affine(B._FP, B.G1_GEN), k))
    nkp = (kp[0], (-kp[1]) % B.P)
    kq = B._to_affine(B._FP2, B._jac_mul(B._FP2, B._from_affine(B._FP2, B.G2_GEN), k))
    g1 = B._to_affine(B._FP, B._from_affine(B._FP, B.G1_GEN))
    g2 = B._to_affine(B._FP2, B._from_affine(B._FP2, B.G2_GEN))
    assert B._pairings_product_is_one([(nkp, g2), (g1, kq)])
    assert not B._pairings_product_is_one([(kp, g2), (g1, kq)])
