"""BLS12-381 key type (reference: crypto/bls12381/key_bls12381.go).

Pairing correctness is checked structurally (bilinearity, negative
controls) since the implementation is self-contained; serialization is
pinned against the universally-known ZCash-format compressed
generators.
"""

import pytest

from cometbft_tpu.crypto import bls12381 as bls

# The compressed generators are fixed, publicly-known constants — any
# BLS12-381 library prints these exact bytes.
G1_GEN_COMPRESSED = (
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac586c"
    "55e83ff97a1aeffb3af00adb22c6bb"
)
G2_GEN_COMPRESSED = (
    "93e02b6052719f607dacd3a088274f65596bd0d09920b61ab5da61bbdc7f504933"
    "4cf11213945d57e5ac7d055d042b7e024aa2b2f08f0a91260805272dc51051c6e4"
    "7ad4fa403b02b4510b647ae3d1770bac0326a805bbefd48056c8c121bdb8"
)


def test_generator_serialization_pinned():
    assert bls._g1_compress(bls.G1_GEN).hex() == G1_GEN_COMPRESSED
    assert bls._g2_compress(bls.G2_GEN).hex() == G2_GEN_COMPRESSED
    assert bls._g1_decompress(bytes.fromhex(G1_GEN_COMPRESSED)) == bls.G1_GEN
    assert bls._g2_decompress(bytes.fromhex(G2_GEN_COMPRESSED)) == bls.G2_GEN


def test_subgroup_and_curve_checks():
    assert bls._on_curve(bls._FP, bls.G1_GEN)
    assert bls._on_curve(bls._FP2, bls.G2_GEN)
    assert bls._in_subgroup(bls._FP, bls.G1_GEN)
    assert bls._in_subgroup(bls._FP2, bls.G2_GEN)
    # r * G = infinity exactly
    assert bls._jac_mul(bls._FP, bls._from_affine(bls._FP, bls.G1_GEN), bls.R)[2] == 0


def test_infinity_pubkey_rejected():
    inf = bytes([0xC0]) + bytes(47)
    with pytest.raises(ValueError, match="infinite"):
        bls.PubKey(inf)


def test_malformed_points_rejected():
    with pytest.raises(ValueError):
        bls._g1_decompress(bytes(48))  # no compression flag
    bad_x = bytearray(bytes.fromhex(G1_GEN_COMPRESSED))
    bad_x[-1] ^= 1
    # flipping x usually leaves the curve; accept either not-on-curve or
    # a different valid point — but never the generator
    try:
        pt = bls._g1_decompress(bytes(bad_x))
        assert pt != bls.G1_GEN
    except ValueError:
        pass


def test_sign_verify_and_tamper():
    sk = bls.PrivKey.from_secret(b"validator-1")
    pk = sk.pub_key()
    assert len(pk.data) == bls.PUBKEY_SIZE
    assert len(pk.address()) == 20
    msg = b"precommit|height=5|round=0"
    sig = sk.sign(msg)
    assert len(sig) == bls.SIG_SIZE
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg + b"!", sig)
    assert not pk.verify_signature(msg, sig[:-1] + bytes([sig[-1] ^ 1]))
    # signature by a different key
    sk2 = bls.PrivKey.from_secret(b"validator-2")
    assert not sk2.pub_key().verify_signature(msg, sig)


def test_deterministic_keygen():
    a = bls.PrivKey.from_secret(b"seed")
    b = bls.PrivKey.from_secret(b"seed")
    assert a.bytes() == b.bytes()
    assert a.pub_key().data == b.pub_key().data
    assert bls.PrivKey.from_secret(b"other").bytes() != a.bytes()


@pytest.mark.slow
def test_aggregate_verify_distinct_messages():
    sks = [bls.PrivKey.from_secret(b"agg-%d" % i) for i in range(3)]
    pks = [sk.pub_key() for sk in sks]
    msgs = [b"vote-%d" % i for i in range(3)]
    agg = bls.aggregate_signatures([sk.sign(m) for sk, m in zip(sks, msgs)])
    assert len(agg) == bls.SIG_SIZE
    assert bls.aggregate_verify(pks, msgs, agg)
    # swap two messages: must fail
    assert not bls.aggregate_verify(pks, [msgs[1], msgs[0], msgs[2]], agg)


@pytest.mark.slow
def test_fast_aggregate_verify_same_message():
    sks = [bls.PrivKey.from_secret(b"fagg-%d" % i) for i in range(4)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"commit|height=9"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert bls.fast_aggregate_verify(pks, msg, agg)
    # missing one signer
    partial = bls.aggregate_signatures([sk.sign(msg) for sk in sks[:3]])
    assert not bls.fast_aggregate_verify(pks, msg, partial)


def test_proto_roundtrip():
    from cometbft_tpu.crypto import encoding

    pk = bls.PrivKey.from_secret(b"proto").pub_key()
    back = encoding.pubkey_from_proto(encoding.pubkey_to_proto(pk))
    assert isinstance(back, bls.PubKey) and back.data == pk.data


@pytest.mark.slow
def test_aggregate_verify_rejects_duplicate_messages():
    """Basic (NUL) scheme: duplicate messages reopen the rogue-key attack,
    so AggregateVerify must reject them outright."""
    sks = [bls.PrivKey.from_secret(b"dup-%d" % i) for i in range(2)]
    pks = [sk.pub_key() for sk in sks]
    msg = b"same-message"
    agg = bls.aggregate_signatures([sk.sign(msg) for sk in sks])
    assert not bls.aggregate_verify(pks, [msg, msg], agg)


@pytest.mark.slow
def test_proof_of_possession():
    sk = bls.PrivKey.from_secret(b"pop-1")
    pk = sk.pub_key()
    proof = bls.pop_prove(sk)
    assert bls.pop_verify(pk, proof)
    # a PoP for a different key does not transfer
    other = bls.PrivKey.from_secret(b"pop-2").pub_key()
    assert not bls.pop_verify(other, proof)
    # an ordinary signature over pk bytes is NOT a PoP (different DST)
    assert not bls.pop_verify(pk, sk.sign(pk.data))


def test_svdw_exceptional_inputs_map_to_curve():
    """RFC 9380 inv0 convention: u with (1 ± g(Z)·u²) = 0 (tv3 == 0) must
    map onto the curve instead of crashing (the old x=Z special case
    raised TypeError when g(Z) was non-square)."""
    from cometbft_tpu.crypto import bls12381 as B

    hit = 0
    for sign in (1, -1):
        tgt = B.f2_inv(B._SVDW_GZ)
        if sign == -1:
            tgt = B.f2_neg(tgt)
        u = B.f2_sqrt(tgt)
        if u is None:
            continue
        hit += 1
        x, y = B._map_to_curve_svdw(u)
        g = B.f2_add(B.f2_mul(B.f2_sqr(x), x), B._FP2.b)
        assert B.f2_sqr(y) == g, "mapped point must satisfy y^2 = g(x)"
    assert hit, "at least one exceptional u exists in Fp2"


def test_native_pairing_core_matches_python():
    """native/bls381.cc must agree with the pure-Python pairing: full
    pairing values coefficient-by-coefficient, and the product check on
    both a valid signature relation and a broken one."""
    import ctypes
    import random

    from cometbft_tpu.crypto import bls12381 as B

    lib = B._native_pairing_lib()
    if lib is None:
        import pytest

        pytest.skip("native pairing core unavailable")
    lib.bls381_pairing.restype = None

    rnd = random.Random(7)
    for _ in range(2):
        k1 = rnd.randrange(1, B.R)
        k2 = rnd.randrange(1, B.R)
        p = B._to_affine(B._FP, B._jac_mul(B._FP, B._from_affine(B._FP, B.G1_GEN), k1))
        q = B._to_affine(
            B._FP2, B._jac_mul(B._FP2, B._from_affine(B._FP2, B.G2_GEN), k2)
        )
        want = B._final_exp(B._miller(q, p))
        a1 = (ctypes.c_uint64 * 12)(*(B._limbs6(p[0]) + B._limbs6(p[1])))
        a2 = (ctypes.c_uint64 * 24)(
            *(B._limbs6(q[0][0]) + B._limbs6(q[0][1])
              + B._limbs6(q[1][0]) + B._limbs6(q[1][1]))
        )
        out = (ctypes.c_uint64 * 72)()
        lib.bls381_pairing(a1, a2, out)
        got = tuple(
            (
                sum(out[i * 12 + j] << (64 * j) for j in range(6)),
                sum(out[i * 12 + 6 + j] << (64 * j) for j in range(6)),
            )
            for i in range(6)
        )
        assert got == want

    # bilinearity through the product check: e(-kP, Q) * e(P, kQ) == 1
    k = rnd.randrange(2, B.R)
    kp = B._to_affine(B._FP, B._jac_mul(B._FP, B._from_affine(B._FP, B.G1_GEN), k))
    nkp = (kp[0], (-kp[1]) % B.P)
    kq = B._to_affine(B._FP2, B._jac_mul(B._FP2, B._from_affine(B._FP2, B.G2_GEN), k))
    g1 = B._to_affine(B._FP, B._from_affine(B._FP, B.G1_GEN))
    g2 = B._to_affine(B._FP2, B._from_affine(B._FP2, B.G2_GEN))
    assert B._pairings_product_is_one([(nkp, g2), (g1, kq)])
    assert not B._pairings_product_is_one([(kp, g2), (g1, kq)])
