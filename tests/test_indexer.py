"""Tx/block indexers + indexer service (reference: state/txindex/kv/
kv_test.go, indexer_service_test.go)."""

import time

import pytest

from cometbft_tpu.indexer import (
    BlockIndexer,
    IndexerService,
    TxIndexer,
    tx_hash,
)
from cometbft_tpu.store.db import MemDB
from cometbft_tpu.types.event_bus import EventBus
from cometbft_tpu.wire import abci_pb as apb


def _result(code=0, events=None):
    return apb.ExecTxResult(
        code=code,
        data=b"",
        log="",
        events=[
            apb.Event(
                type=t,
                attributes=[
                    apb.EventAttribute(key=k, value=v) for k, v in attrs
                ],
            )
            for t, attrs in (events or [])
        ],
    )


def test_tx_indexer_index_get_search():
    idx = TxIndexer(MemDB())
    txs = [b"alpha=1", b"beta=2", b"gamma=3"]
    for i, tx in enumerate(txs):
        idx.index(
            5, i, tx, _result(),
            {"transfer.sender": [f"addr{i}"], "transfer.amount": [str(10 * i)]},
        )
    idx.index(6, 0, b"delta=4", _result(), {"transfer.sender": ["addr1"]})

    rec = idx.get(tx_hash(b"beta=2"))
    assert rec is not None and rec["height"] == 5 and rec["index"] == 1

    # event '=' condition hits the secondary index
    hits = idx.search("transfer.sender='addr1'")
    assert len(hits) == 2 and {r["height"] for r in hits} == {5, 6}

    # AND with a height bound
    hits = idx.search("transfer.sender='addr1' AND tx.height=6")
    assert len(hits) == 1 and hits[0]["height"] == 6

    # range condition over an attribute
    hits = idx.search("transfer.amount>5")
    assert {r["index"] for r in hits} == {1, 2}

    # by hash — either case matches (values are stored uppercase)
    hits = idx.search(f"tx.hash='{tx_hash(b'gamma=3').hex().upper()}'")
    assert len(hits) == 1 and hits[0]["index"] == 2
    hits = idx.search(f"tx.hash='{tx_hash(b'gamma=3').hex()}'")
    assert len(hits) == 1 and hits[0]["index"] == 2


def test_block_indexer_search():
    idx = BlockIndexer(MemDB())
    idx.index(10, {"rewards.amount": ["5"], "block.proposer": ["aa"]})
    idx.index(11, {"rewards.amount": ["7"], "block.proposer": ["bb"]})
    idx.index(12, {"block.proposer": ["aa"]})
    assert idx.has(11) and not idx.has(13)
    assert idx.search("block.proposer='aa'") == [10, 12]
    assert idx.search("rewards.amount>5") == [11]
    assert idx.search("block.height=12") == [12]


def test_indexer_service_feeds_from_event_bus():
    bus = EventBus()
    txi, bli = TxIndexer(MemDB()), BlockIndexer(MemDB())
    svc = IndexerService(txi, bli, bus)
    svc.start()
    try:
        bus.publish_tx(
            7, 0, b"k=v",
            _result(events=[("transfer", [("sender", "s1")])]),
        )
        bus.publish_new_block_events(
            7, [apb.Event(type="mint", attributes=[apb.EventAttribute(key="amt", value="3")])], 1
        )
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
            txi.get(tx_hash(b"k=v")) is None or not bli.has(7)
        ):
            time.sleep(0.02)
        rec = txi.get(tx_hash(b"k=v"))
        assert rec is not None and rec["height"] == 7
        assert txi.search("transfer.sender='s1'")
        assert bli.search("mint.amt=3") == [7]
    finally:
        svc.stop()
