"""Node health sentinel (cometbft_tpu/utils/healthmon): hang-proof
probe judging, the ok→degraded→wedged state machine, heartbeat
staleness blame, forensics artifact rate-limiting, the /tpu_health
route, and off-by-default zero overhead.

All fast and CPU-only: probes are stubbed (an Event-blocked stub stands
in for a wedged device tunnel — the real subprocess probe is exercised
once by the bench-harness tests), periods are tens of milliseconds, and
the sentinel is driven deterministically through tick() except for the
one end-to-end test that runs the real thread.
"""

import os
import threading
import time

import pytest

from cometbft_tpu.utils import healthmon
from cometbft_tpu.utils.flightrec import recorder as flightrec
from cometbft_tpu.utils.healthmon import (
    STATE_DEGRADED,
    STATE_OK,
    STATE_WEDGED,
    HealthMonitor,
    ProbeResult,
)
from cometbft_tpu.utils.metrics import hub as mhub

WAIT = 10.0


def _ok_probe(timeout_s):
    return ProbeResult(True, "cpu", 0.001)


def _fail_probe(timeout_s):
    return ProbeResult(False, "probe exited 1", 0.002)


class _BlockingProbe:
    """A probe wedged like the real tunnel: blocks until released (or
    forever), which the sentinel must survive without ever blocking."""

    def __init__(self):
        self.release = threading.Event()
        self.calls = 0

    def __call__(self, timeout_s):
        self.calls += 1
        self.release.wait(WAIT)
        return ProbeResult(True, "late", 0.0)


@pytest.fixture
def mon():
    """Construct-and-install monitors; always uninstalled afterwards so
    beats drop back to the zero-overhead no-op for every other test."""
    made = []

    def make(**kw):
        kw.setdefault("probe_period_s", 0.05)
        kw.setdefault("probe_timeout_s", 0.05)
        kw.setdefault("probe_grace_s", 0.05)
        kw.setdefault("artifact_min_interval_s", 0.0)
        m = HealthMonitor(**kw)
        made.append(m)
        healthmon.install(m)
        return m

    yield make
    healthmon.uninstall()
    for m in made:
        m.stop()


# ------------------------------------------------------- state machine


def test_ok_probe_keeps_state_ok(mon, tmp_path):
    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path))
    m.tick()
    deadline = time.monotonic() + WAIT
    while m.snapshot()["probe_attempts"] == 0:
        assert time.monotonic() < deadline
        time.sleep(0.005)
        m.tick()
    snap = m.snapshot()
    assert snap["state"] == STATE_OK
    assert snap["ready"] is True
    assert snap["last_probe"]["ok"] is True
    assert snap["consecutive_probe_failures"] == 0
    assert list(tmp_path.iterdir()) == []  # healthy: no forensics


def test_failing_probe_walks_degraded_then_wedged(mon, tmp_path):
    m = mon(probe_fn=_fail_probe, wedge_after=2, artifact_dir=str(tmp_path))
    now = time.monotonic()
    m.tick(now)  # kicks probe 1 (worker ingests the failure async)
    deadline = time.monotonic() + WAIT
    while m.snapshot()["consecutive_probe_failures"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    m.tick(now + 0.01)  # state-machine pass; next probe period not reached
    assert m.snapshot()["state"] == STATE_DEGRADED
    # second probe period -> second failure -> wedged
    m.tick(now + 0.06)
    deadline = time.monotonic() + WAIT
    while m.snapshot()["consecutive_probe_failures"] < 2:
        assert time.monotonic() < deadline
        time.sleep(0.005)
    m.tick(now + 0.07)
    snap = m.snapshot()
    assert snap["state"] == STATE_WEDGED
    assert snap["ready"] is False


def test_blocking_probe_never_blocks_sentinel_and_wedges(mon, tmp_path):
    """The acceptance scenario: a probe that blocks PAST its deadline
    (the stubbed wedged tunnel) drives the state to wedged via judged
    hang failures, and every tick() returns promptly — the sentinel
    itself is hang-proof."""
    probe = _BlockingProbe()
    m = mon(probe_fn=probe, wedge_after=2, artifact_dir=str(tmp_path))
    t0 = time.monotonic()
    m.tick(t0)  # kicks the probe; worker thread now parked in the stub
    assert time.monotonic() - t0 < 0.5  # tick returned, probe still stuck
    # past deadline+grace: judged as a hang -> failure 1 -> degraded
    m.tick(t0 + 0.11)
    snap = m.snapshot()
    assert snap["consecutive_probe_failures"] == 1
    assert snap["state"] == STATE_DEGRADED
    assert snap["last_probe"]["timed_out"] is True
    # next probe period with the worker STILL stuck: failure 2 -> wedged
    m.tick(t0 + 0.17)
    snap = m.snapshot()
    assert snap["consecutive_probe_failures"] == 2
    assert snap["state"] == STATE_WEDGED
    assert probe.calls == 1  # never piles up probe threads on a wedge
    probe.release.set()


def test_probe_recovery_snaps_back_to_ok(mon, tmp_path):
    results = [ProbeResult(False, "probe exited 1", 0.0)]

    def probe(timeout_s):
        return results[-1]

    m = mon(probe_fn=probe, wedge_after=1, artifact_dir=str(tmp_path))
    deadline = time.monotonic() + WAIT
    while m.snapshot()["state"] != STATE_WEDGED:
        assert time.monotonic() < deadline
        m.tick()
        time.sleep(0.005)
    results.append(ProbeResult(True, "tpu", 0.01))
    deadline = time.monotonic() + WAIT
    while m.snapshot()["state"] != STATE_OK:
        assert time.monotonic() < deadline
        m.tick()
        time.sleep(0.005)
    snap = m.snapshot()
    assert snap["consecutive_probe_failures"] == 0
    assert snap["ready"] is True


# ----------------------------------------------------------- heartbeats


def test_stale_heartbeat_blames_exact_loop(mon, tmp_path):
    m = mon(
        probe_fn=_ok_probe,
        artifact_dir=str(tmp_path),
        loops={"my-loop": 0.05, "other-loop": 30.0},
    )
    healthmon.beat("my-loop")
    healthmon.beat("other-loop")
    m.tick()
    assert m.snapshot()["stale_loops"] == []
    time.sleep(0.08)
    m.tick()
    snap = m.snapshot()
    assert snap["state"] == STATE_DEGRADED
    assert snap["stale_loops"] == ["my-loop"]  # other-loop NOT blamed
    assert snap["loops"]["my-loop"]["stale"] is True
    assert snap["loops"]["other-loop"]["stale"] is False
    # the artifact blames the exact loop (and only it) in its reason line
    arts = list(tmp_path.iterdir())
    assert len(arts) == 1
    text = arts[0].read_text()
    reason = next(l for l in text.splitlines() if l.startswith("reason:"))
    assert "stale heartbeat(s): my-loop" in reason
    assert "other-loop" not in reason
    # a fresh beat clears the staleness and the state
    healthmon.beat("my-loop")
    m.tick()
    assert m.snapshot()["state"] == STATE_OK


def test_retired_loop_is_not_audited(mon, tmp_path):
    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path),
            loops={"done-loop": 0.02})
    healthmon.beat("done-loop")
    healthmon.retire("done-loop")  # clean exit (blocksync handoff)
    time.sleep(0.05)
    m.tick()
    snap = m.snapshot()
    assert snap["state"] == STATE_OK
    assert "done-loop" not in snap["loops"]


def test_informational_loop_reported_but_never_stale(mon, tmp_path):
    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path),
            loops={"switch-accept": None})
    healthmon.beat("switch-accept")
    time.sleep(0.05)
    m.tick()
    snap = m.snapshot()
    assert snap["state"] == STATE_OK
    assert snap["loops"]["switch-accept"]["deadline_s"] is None
    assert snap["loops"]["switch-accept"]["age_s"] >= 0.0


# ------------------------------------------------------------ forensics


def test_exactly_one_artifact_per_incident(mon, tmp_path):
    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path),
            loops={"loopy": 0.03})
    healthmon.beat("loopy")
    time.sleep(0.05)
    for _ in range(5):  # stays stale across many audits
        m.tick()
        time.sleep(0.005)
    assert len(list(tmp_path.iterdir())) == 1  # ONE per incident
    # recovery closes the incident ...
    healthmon.beat("loopy")
    m.tick()
    assert m.snapshot()["state"] == STATE_OK
    # ... and a NEW incident captures a second artifact
    time.sleep(0.05)
    m.tick()
    assert m.snapshot()["state"] == STATE_DEGRADED
    assert len(list(tmp_path.iterdir())) == 2


def test_artifact_min_interval_rate_limits_flapping(mon, tmp_path):
    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path),
            artifact_min_interval_s=3600.0, loops={"flappy": 0.03})
    healthmon.beat("flappy")
    time.sleep(0.05)
    m.tick()
    assert len(list(tmp_path.iterdir())) == 1
    healthmon.beat("flappy")
    m.tick()  # recovered
    time.sleep(0.05)
    m.tick()  # second incident inside the interval floor
    assert m.snapshot()["state"] == STATE_DEGRADED
    assert len(list(tmp_path.iterdir())) == 1  # floor held


def test_artifact_contents_and_snapshot_pointer(mon, tmp_path):
    m = mon(probe_fn=_fail_probe, wedge_after=1, artifact_dir=str(tmp_path))
    t0 = time.monotonic()
    m.tick(t0)
    deadline = time.monotonic() + WAIT
    while m.snapshot()["last_artifact"] is None:
        assert time.monotonic() < deadline
        time.sleep(0.005)
        m.tick()
    path = m.snapshot()["last_artifact"]
    assert os.path.dirname(path) == str(tmp_path)
    text = open(path).read()
    assert "=== stall forensics ===" in text
    assert "consecutive probe failure(s)" in text
    assert "=== health snapshot ===" in text
    assert "=== verify service ===" in text  # in-flight batch ages live here
    assert "=== consensus flight recorder ===" in text
    assert "=== threads ===" in text


# --------------------------------------- transitions: flightrec + metrics


def test_transition_emits_flightrec_event_and_metrics(mon, tmp_path):
    before = [
        e for e in flightrec().dump()["entries"] if e["kind"] == "health"
    ]
    m = mon(probe_fn=_fail_probe, wedge_after=1, artifact_dir=str(tmp_path))
    t0 = time.monotonic()
    m.tick(t0)
    deadline = time.monotonic() + WAIT
    while m.snapshot()["state"] != STATE_WEDGED:
        assert time.monotonic() < deadline
        time.sleep(0.005)
        m.tick()
    events = [
        e for e in flightrec().dump()["entries"] if e["kind"] == "health"
    ]
    assert len(events) == len(before) + 1  # ONE transition event
    ev = events[-1]
    assert ev["detail"]["state"] == STATE_WEDGED
    assert ev["detail"]["prev"] == STATE_OK
    assert mhub().health_state.value() == 2.0
    assert mhub().health_probe_consec_failures.value() >= 1.0
    # recovery transitions back and the gauge follows
    m._probe_fn = _ok_probe
    deadline = time.monotonic() + WAIT
    while m.snapshot()["state"] != STATE_OK:
        assert time.monotonic() < deadline
        m.tick()
        time.sleep(0.005)
    assert mhub().health_state.value() == 0.0


# ------------------------------------------------- end-to-end (real thread)


def test_sentinel_thread_end_to_end_wedge(mon, tmp_path):
    """The acceptance criterion, with the real sentinel thread: a
    stubbed wedged probe (blocks past its deadline) drives the state to
    wedged with NO caller thread ever blocking, emits exactly one
    forensics artifact + flight-recorder event + health_state
    transition, and /tpu_health reports it all."""
    probe = _BlockingProbe()
    m = mon(
        probe_fn=probe,
        probe_period_s=0.04,
        probe_timeout_s=0.04,
        probe_grace_s=0.02,
        wedge_after=2,
        artifact_dir=str(tmp_path),
    )
    m.start()
    try:
        # node loops keep beating while the sentinel works — never blocked
        t0 = time.monotonic()
        while time.monotonic() - t0 < WAIT:
            beat_t0 = time.monotonic()
            healthmon.beat("cs-receive")
            assert time.monotonic() - beat_t0 < 0.1
            if healthmon.snapshot()["state"] == STATE_WEDGED:
                break
            time.sleep(0.01)
        snap = healthmon.snapshot()
        assert snap["state"] == STATE_WEDGED, snap
        assert snap["ready"] is False
        assert snap["consecutive_probe_failures"] >= 2
        assert snap["last_probe"]["timed_out"] is True
        assert "cs-receive" in snap["loops"]
        arts = list(tmp_path.iterdir())
        assert len(arts) == 1  # exactly one artifact for the incident
        assert snap["last_artifact"] == str(arts[0])
        wedge_events = [
            e
            for e in flightrec().dump()["entries"]
            if e["kind"] == "health"
            and e["detail"]["state"] == STATE_WEDGED
        ]
        assert len(wedge_events) >= 1
        assert mhub().health_state.value() == 2.0
    finally:
        probe.release.set()
        m.stop()


# ------------------------------------------------------------- surfaces


def test_tpu_health_route_registered_and_health_stays_empty():
    from cometbft_tpu.rpc.core import ROUTES, Environment

    assert "tpu_health" in ROUTES
    assert ROUTES["tpu_health"][0] == ""  # no params
    env = Environment(object())
    # wire-compat: /health is {} by contract, whatever the sentinel says
    assert env.health() == {}


def test_tpu_health_serves_snapshot(mon, tmp_path):
    from cometbft_tpu.rpc.core import Environment

    m = mon(probe_fn=_ok_probe, artifact_dir=str(tmp_path))
    m.tick()
    out = Environment(object()).tpu_health()
    assert out["enabled"] is True
    assert out["state"] in (STATE_OK, STATE_DEGRADED, STATE_WEDGED)
    import json

    json.dumps(out)  # the RPC layer serializes it verbatim


def test_disabled_monitor_is_zero_overhead_noop():
    assert healthmon.monitor() is None  # fixture teardown guarantees this
    healthmon.beat("anything")  # must not record, raise, or allocate state
    healthmon.retire("anything")
    snap = healthmon.snapshot()
    assert snap["enabled"] is False
    assert snap["ready"] is True  # no signal = don't drain the node
    assert snap["loops"] == {}
    # maybe_start honors the off-by-default knob
    assert os.environ.get("COMETBFT_TPU_HEALTH") in (None, "", "0")
    assert healthmon.maybe_start() is None
    assert healthmon.monitor() is None


# ------------------------------------------------ shared probe (bench.py)


def test_probe_devices_ok_on_cpu():
    """The real subprocess probe against the CPU backend: the exact
    implementation bench.py imports (BENCH r03-r05's bespoke copy is
    gone).  The child forces nothing — this test environment already
    pins JAX_PLATFORMS=cpu for children via the conftest scrub."""
    res = healthmon.probe_devices(60.0)
    assert res.ok is True
    assert res.timed_out is False
    assert res.latency_s < 60.0
    assert res.detail  # platform name


def test_bench_imports_shared_probe():
    """bench.py's wedge path runs THE library probe, not a copy: the
    module source references healthmon.probe_devices and carries no
    Popen of its own."""
    src = open(os.path.join(os.path.dirname(__file__), "..", "bench.py")).read()
    assert "healthmon" in src
    assert "probe_devices" in src
    assert "subprocess.Popen" not in src  # the bespoke copy is gone
    assert "os.killpg" not in src  # kill escalation lives in the library now


# --------------------------------------------- verifysvc in-flight ages


def test_verifysvc_stats_report_in_flight_batch_ages():
    from cometbft_tpu.verifysvc.service import Klass, VerifyService

    gate = threading.Event()

    class SlowBV:
        def __init__(self):
            self.items = []

        def add(self, pub, msg, sig):
            self.items.append((pub, msg, sig))

        def submit(self):
            return ("dev", None)

        def collect(self, ticket):
            gate.wait(WAIT)
            return True, [True] * len(self.items)

    s = VerifyService(batch_max=64, queue_max=1024)
    s._make_verifier = lambda mode: SlowBV()
    try:
        ticket = s.submit([(b"p" * 32, b"m", b"s" * 64)], Klass.MEMPOOL)
        deadline = time.monotonic() + WAIT
        inflight = []
        while not inflight:
            assert time.monotonic() < deadline
            inflight = s.stats()["in_flight"]
            time.sleep(0.005)
        assert inflight[0]["class"] == "mempool"
        assert inflight[0]["sigs"] == 1
        assert inflight[0]["age_s"] >= 0.0
        gate.set()
        ok, per = ticket.collect(WAIT)
        assert ok and per == [True]
        deadline = time.monotonic() + WAIT
        while s.stats()["in_flight"]:
            assert time.monotonic() < deadline
            time.sleep(0.005)
    finally:
        gate.set()
        s.stop()


def test_verifysvc_stats_bounded_lock_wait():
    """The sentinel's forensics pass a lock timeout: stats() must answer
    with the lock-free tallies even while the scheduler lock is held —
    diagnosing a wedge must never block on the wedge."""
    from cometbft_tpu.verifysvc.service import VerifyService

    s = VerifyService()
    held = threading.Event()
    release = threading.Event()

    def holder():
        with s._cond:
            held.set()
            release.wait(WAIT)

    t = threading.Thread(target=holder, name="test-lock-holder")
    t.start()
    try:
        assert held.wait(WAIT)
        st = s.stats(lock_timeout=0.05)
        assert st["queued"] == {"lock_busy": True}
        assert "in_flight" in st and "dispatched_batches" in st
    finally:
        release.set()
        t.join(timeout=WAIT)
        s.stop()
