"""Known-answer + adversarial corpus for the HOST secp256k1 lanes
(crypto/secp256k1, crypto/secp256k1eth).

The host lane is the fallback verdict ORACLE of the MODE_SECP
verify-service lane (models/secp_verifier routes failover / breaker /
backpressure / sub-threshold batches through it, and the device kernel
is pinned bit-identical to it) — so it needs its own adversarial
corpus, not just round-trip tests.

KAT sources: the published secp256k1 RFC 6979 deterministic-nonce
vectors (the trezor / python-ecdsa suite — RFC 6979 itself has no
secp256k1 profile, these are the de-facto ones every wallet pins) and
Wycheproof-style negative cases: high-s rejection, r = 0 / s = 0,
r/s >= n, wrong lengths, non-canonical pubkey encodings, and the
point-at-infinity / not-on-curve edges.
"""

import hashlib

import pytest

from cometbft_tpu.crypto import secp256k1 as c
from cometbft_tpu.crypto import secp256k1eth as eth
from cometbft_tpu.crypto.keccak import keccak256

# (privkey scalar, message, expected r, expected s) — published
# secp256k1 RFC 6979 vectors (low-s normalized, as the Cosmos lane
# emits them; each independently reproduced by trezor-firmware and
# python-ecdsa test suites)
RFC6979_VECTORS = [
    (
        1,
        b"Satoshi Nakamoto",
        0x934B1EA10A4B3C1757E2B0C017D0B6143CE3C9A7E6A4A49860D7A6AB210EE3D8,
        0x2442CE9D2B916064108014783E923EC36B49743E2FFA1C4496F01A512AAFD9E5,
    ),
    (
        1,
        b"All those moments will be lost in time, like tears in rain. "
        b"Time to die...",
        0x8600DBD41E348FE5C9465AB92D23E3DB8B98B873BEECD930736488696438CB6B,
        0x547FE64427496DB33BF66019DACBF0039C04199ABB0122918601DB38A72CFC21,
    ),
    (
        c.N - 1,
        b"Satoshi Nakamoto",
        0xFD567D121DB66E382991534ADA77A6BD3106F0A1098C231E47993447CD6AF2D0,
        0x6B39CD0EB1BC8603E159EF5C20A5C8AD685A45B06CE9BEBED3F153D10D93BED5,
    ),
    (
        0xF8B8AF8CE3C7CCA5E300D33939540C10D45CE001B8F252BFBC57BA0342904181,
        b"Alan Turing",
        0x7063AE83E7F62BBB171798131B4A0564B956930092B33B07B395615D9EC7E15C,
        0x58DFCC1E00A35E1572F366FFE34BA0FC47DB1E7189759B9FB233C5B05AB388EA,
    ),
]


@pytest.mark.parametrize("d,msg,er,es", RFC6979_VECTORS)
def test_rfc6979_known_answers(d, msg, er, es):
    sk = c.PrivKey(d.to_bytes(32, "big"))
    sig = sk.sign(msg)
    assert int.from_bytes(sig[:32], "big") == er
    assert int.from_bytes(sig[32:], "big") == es
    assert sk.pub_key().verify_signature(msg, sig)


def _sig(r: int, s: int) -> bytes:
    return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def test_high_s_rejected():
    """The low-s malleability rule: (r, n - s) satisfies the raw ECDSA
    equation but MUST be rejected (Cosmos rule; eth lane identically)."""
    sk = c.PrivKey.from_seed(b"kat-high-s")
    pk = sk.pub_key()
    msg = b"malleability"
    sig = sk.sign(msg)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    assert pk.verify_signature(msg, sig)
    assert not pk.verify_signature(msg, _sig(r, c.N - s))


def test_zero_and_range_scalars_rejected():
    sk = c.PrivKey.from_seed(b"kat-range")
    pk = sk.pub_key()
    msg = b"ranges"
    sig = sk.sign(msg)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    assert not pk.verify_signature(msg, _sig(0, s))  # r = 0
    assert not pk.verify_signature(msg, _sig(r, 0))  # s = 0
    assert not pk.verify_signature(msg, _sig(c.N, s))  # r = n
    assert not pk.verify_signature(msg, _sig(c.N + 1, s))  # r > n
    assert not pk.verify_signature(msg, _sig(r, c.N))  # s = n


def test_wrong_length_signatures_rejected():
    sk = c.PrivKey.from_seed(b"kat-len")
    pk = sk.pub_key()
    msg = b"lengths"
    sig = sk.sign(msg)
    assert not pk.verify_signature(msg, sig[:-1])
    assert not pk.verify_signature(msg, sig + b"\x00")
    assert not pk.verify_signature(msg, b"")


def test_noncanonical_pubkey_encodings_rejected():
    """Bad prefix byte, x >= p, and x-not-on-curve compressed keys must
    all refuse to construct (PubKey validates eagerly)."""
    sk = c.PrivKey.from_seed(b"kat-enc")
    good = sk.pub_key().data
    with pytest.raises(ValueError):
        c.PubKey(b"\x04" + good[1:])  # uncompressed prefix, 33 bytes
    with pytest.raises(ValueError):
        c.PubKey(b"\x05" + good[1:])  # junk prefix
    with pytest.raises(ValueError):
        c.PubKey(bytes([2]) + c.P.to_bytes(32, "big"))  # x = p
    with pytest.raises(ValueError):
        c.PubKey(good[:-1])  # truncated
    with pytest.raises(ValueError):
        c.PubKey(good + b"\x00")  # oversized
    # x with no curve point: x^3 + 7 a quadratic non-residue
    x = 5
    while True:
        y2 = (pow(x, 3, c.P) + c.B) % c.P
        y = pow(y2, (c.P + 1) // 4, c.P)
        if y * y % c.P != y2:
            break
        x += 1
    with pytest.raises(ValueError):
        c.PubKey(bytes([2]) + x.to_bytes(32, "big"))


def test_point_at_infinity_edge():
    """u1*G + u2*Q = infinity can be forced with crafted (r, s): pick
    k with R = k*G, then for the verifying equation to hit infinity
    take e = -r*d*... — simplest construction: e = 0 path is blocked
    (e is a hash), so craft via s = e/r' ... Instead pin the direct
    edge: a signature whose verification point WOULD be infinity is
    rejected.  With Q = -(e/r mod n)^-1... we construct it explicitly:
    choose u1, u2 with u1*G = -(u2*Q); then r = x(inf) is undefined —
    the host returns False via the `pt is None` branch.  We reach that
    branch with d = -e/r mod n so that u1*G + u2*Q = (e + r*d)/s * G
    = 0 * G."""
    msg = b"infinity-edge"
    e = int.from_bytes(hashlib.sha256(msg).digest(), "big") % c.N
    # pick any r from a real curve point, then d = -e/r mod n
    k = 12345
    r = c._mul(k, c.G)[0] % c.N
    d = (-e) * c._inv(r, c.N) % c.N
    pk = c.PrivKey(d.to_bytes(32, "big")).pub_key()
    s = 2  # any valid low-s scalar: (e + r*d)/s = 0 regardless of s
    assert not pk.verify_signature(msg, _sig(r, s))


# ---------------------------------------------------------------- eth lane


def test_eth_sign_recover_roundtrip():
    sk = eth.PrivKey.from_seed(b"kat-eth")
    pk = sk.pub_key()
    msg = b"eth-roundtrip"
    sig = sk.sign(msg)
    assert len(sig) == 65 and sig[64] in (0, 1)
    assert pk.verify_signature(msg, sig)
    assert eth.recover_pubkey(keccak256(msg), sig) == pk.data
    # low-s invariant on the eth wire too
    assert int.from_bytes(sig[32:64], "big") <= c.N // 2


def test_eth_adversarial_edges():
    sk = eth.PrivKey.from_seed(b"kat-eth-adv")
    pk = sk.pub_key()
    msg = b"eth-edges"
    sig = sk.sign(msg)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    v = sig[64]
    # wrong recovery id -> different recovered key -> False
    assert not pk.verify_signature(msg, sig[:64] + bytes([v ^ 1]))
    # v outside {0, 1}
    assert not pk.verify_signature(msg, sig[:64] + bytes([2]))
    # high-s
    assert not pk.verify_signature(
        msg, _sig(r, c.N - s) + bytes([v ^ 1])
    )
    # r/s = 0 and out-of-range
    assert not pk.verify_signature(msg, _sig(0, s) + bytes([v]))
    assert not pk.verify_signature(msg, _sig(r, 0) + bytes([v]))
    assert not pk.verify_signature(msg, _sig(c.N, s) + bytes([v]))
    # wrong length
    assert not pk.verify_signature(msg, sig[:64])
    assert not pk.verify_signature(msg, sig + b"\x00")
    # tampered message
    assert not pk.verify_signature(msg + b"!", sig)


def test_eth_pubkey_encoding_rejected():
    sk = eth.PrivKey.from_seed(b"kat-eth-enc")
    good = sk.pub_key().data
    with pytest.raises(ValueError):
        eth.PubKey(b"\x02" + good[1:33])  # compressed wire, wrong lane
    with pytest.raises(ValueError):
        eth.PubKey(b"\x00" + good[1:])  # bad prefix
    with pytest.raises(ValueError):
        eth.PubKey(good[:-1])  # truncated
    # off-curve (x, y): flip one byte of y
    bad = bytearray(good)
    bad[64] ^= 1
    with pytest.raises(ValueError):
        eth.PubKey(bytes(bad))


def test_cross_lane_verdicts_disagree_on_wire_shape():
    """A cosmos key's signature is not a valid eth signature and vice
    versa — the wire shapes (33/64 vs 65/65, SHA-256 vs Keccak) are
    the lane discriminator models/secp_verifier keys on."""
    cs = c.PrivKey.from_seed(b"kat-cross")
    es = eth.PrivKey.from_seed(b"kat-cross")
    msg = b"cross-lane"
    assert not es.pub_key().verify_signature(msg, cs.sign(msg))
    assert not cs.pub_key().verify_signature(msg, es.sign(msg))


# ----------------------------------------------------- ecrecover lane


def test_ecrecover_privkey1_address_kat():
    """The most widely known derivation KAT: private key 1's address —
    pins the whole recover-then-compare chain against a published
    value, not just internal consistency."""
    sk = eth.RecoverPrivKey((1).to_bytes(32, "big"))
    assert sk.type == "ecrecover"
    addr = sk.pub_key().data
    assert addr.hex() == "7e5f4552091a69125d5dfcb7b8c2659029395bdf"
    msg = b"ecrecover-kat"
    sig = sk.sign(msg)
    assert eth.verify_address_signature(addr, msg, sig)
    recovered = eth.recover_pubkey(keccak256(msg), sig)
    assert keccak256(recovered[1:])[12:] == addr


def test_ecrecover_verdict_is_recover_then_compare():
    """verify_address_signature must equal "recover_pubkey then compare
    derived address" on every row — the bit-identity oracle the device
    ecrecover lane is pinned to."""
    sk = eth.RecoverPrivKey.from_seed(b"kat-rec")
    addr = sk.pub_key().data
    msg = b"rec-oracle"
    sig = sk.sign(msg)
    cases = [
        (addr, msg, sig),
        (b"\x77" * 20, msg, sig),  # wrong address
        (addr, msg + b"!", sig),  # tampered message
        (addr, msg, bytes([sig[0] ^ 1]) + sig[1:]),  # tampered r
        (addr, msg, sig[:64] + bytes([sig[64] ^ 1])),  # flipped v
        (addr, msg, sig[:64] + bytes([2])),  # v out of range
        (addr, msg, _sig(0, 1) + b"\x00"),  # r = 0
        (addr, msg, _sig(c.N, 1) + b"\x00"),  # r >= n
        (addr, msg, sig[:64]),  # wrong length
    ]
    for a, m, sg in cases:
        if len(sg) != 65:
            want = False
        elif int.from_bytes(sg[32:64], "big") > c.N // 2:
            want = False
        else:
            try:
                rec = eth.recover_pubkey(keccak256(m), sg)
                want = keccak256(rec[1:])[12:] == a
            except ValueError:
                want = False
        assert eth.verify_address_signature(a, m, sg) is want, (a[:4], m)


def test_ecrecover_high_s_rejected_even_though_recover_accepts():
    """recover_pubkey itself accepts a high-S signature (with flipped
    v it recovers the same key) — the VERDICT still rejects it, same
    as the eth lane's malleability gate."""
    sk = eth.RecoverPrivKey.from_seed(b"kat-rec-hs")
    addr = sk.pub_key().data
    msg = b"rec-high-s"
    sig = sk.sign(msg)
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:64], "big")
    hs = _sig(r, c.N - s) + bytes([sig[64] ^ 1])
    # the recover half really does succeed and round-trip...
    rec = eth.recover_pubkey(keccak256(msg), hs)
    assert keccak256(rec[1:])[12:] == addr
    # ...but the verdict is False: malleable wire forms are rejected
    assert not eth.verify_address_signature(addr, msg, hs)


def test_recover_pubkey_type_quacks_like_the_others():
    pk = eth.RecoverPrivKey.from_seed(b"kat-rec-shape").pub_key()
    assert pk.type == "ecrecover"
    assert len(pk.bytes()) == eth.ADDRESS_SIZE
    assert pk.address() == pk.bytes()
    with pytest.raises(ValueError):
        eth.RecoverPubKey(b"\x01" * 19)
