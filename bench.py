"""Benchmark of record: VerifyCommit over a 10,000-validator Commit.

Measures the BatchVerifier path the engine actually uses for commit
verification (types/validation.py -> crypto/batch.create_batch_verifier):
the validator-set-keyed comb-table cache (models/comb_verifier.py).  The
timed region is one full verification call — host batch assembly
(vectorized numpy + hashlib SHA-512 challenge digests, ~128 B shipped per
signature) plus the device comb kernel (ops/comb.verify_cached: no
doublings, no pubkey decompression) — i.e. the same work the reference
does on CPU via curve25519-voi in verifyCommitBatch
(types/validation.go:265, crypto/ed25519/ed25519.go:220), with the
expanded-key cache warm on both sides (ed25519.go:43,68 <-> the resident
comb tables, built once per validator set outside the timed region and
reported in table_build_s).

Prints ONE JSON line:
  {"metric": "verify_commit_p50_10k_ms", "value": <p50 ms>, "unit": "ms",
   "vs_baseline": <Go-CPU-baseline / ours, i.e. speedup>, ...}

Baseline: curve25519-voi batch verify ~27.5 us/sig/core on the QA CPUs
(BASELINE.md: 50-60 us single, ~2x batch gain) -> 275 ms for 10k sigs.
"""

from __future__ import annotations

import json
import time

import numpy as np

N = 10_000
GO_CPU_BASELINE_MS = 275.0
WARMUP = 2
ITERS = 10


def main() -> None:
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host

    # One validator set, one commit: distinct keys, per-validator sign-bytes.
    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    pubs = [k.pub_key().data for k in keys]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-bench"
        items.append((pubs[i], msg, sk.sign(msg)))

    # one-time per validator set: comb tables built + kept device-resident
    t0 = time.perf_counter()
    crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
    build_s = time.perf_counter() - t0

    def run_once() -> float:
        v = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
        t0 = time.perf_counter()
        for pub, msg, sig in items:
            v.add(pub, msg, sig)
        ok, per_sig = v.verify()
        dt = (time.perf_counter() - t0) * 1e3
        assert ok and len(per_sig) == N
        return dt

    for _ in range(WARMUP):
        run_once()
    times = sorted(run_once() for _ in range(ITERS))
    p50 = times[len(times) // 2]
    print(
        json.dumps(
            {
                "metric": "verify_commit_p50_10k_ms",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(GO_CPU_BASELINE_MS / p50, 2),
                "table_build_s": round(build_s, 1),
                "verifier": "comb-cached",
            }
        )
    )


if __name__ == "__main__":
    main()
