"""Benchmark of record: VerifyCommit over a 10,000-validator Commit.

Measures the BatchVerifier path the engine actually uses for commit
verification (types/validation.py -> crypto/batch.create_batch_verifier):
the validator-set-keyed comb-table cache (models/comb_verifier.py).  The
timed region is one full verification call — host batch assembly
(vectorized numpy; the SHA-512 challenge digests are computed on device)
plus the device comb kernel (ops/comb.verify_cached: no doublings, no
pubkey decompression) — i.e. the same work the reference does on CPU via
curve25519-voi in verifyCommitBatch (types/validation.go:265,
crypto/ed25519/ed25519.go:220), with the expanded-key cache warm on both
sides (ed25519.go:43,68 <-> the resident comb tables, built once per
validator set outside the timed region and reported in table_build_s).

Prints ONE JSON line and always exits 0:
  {"metric": "verify_commit_p50_10k_ms", "value": <p50 ms>, "unit": "ms",
   "vs_baseline": <Go-CPU-baseline / ours, i.e. speedup>, "phases": {...},
   "phase_attribution": {phase: {"p50_ms", "share_of_wall"}, ...}}
phase_attribution is the per-phase median over ALL timed iterations,
keyed verbatim by the last_timings keys models/comb_verifier.py records
per call (assembly_ms / h2d_dispatch_ms / staging_wait_ms /
device_wait_ms / submit_ms / kernel_ms); BENCH_TRACE=<path> additionally
exports a Chrome trace of the timed region (utils/tracing) and sets
"traced": true so traced values are never compared against untraced
baselines.
On any failure (the round-3 bench died with rc=1 when the TPU backend was
unreachable) the line carries "error" plus whatever phases completed, so
the driver always records a parseable data point.  The backend is probed
in a throwaway subprocess with a hard timeout BEFORE the expensive table
build, because a wedged device tunnel hangs backend init indefinitely
rather than erroring.  When the probe reports backend-unavailable the
line additionally carries "kernelcheck": the CPU-only static contract
pass over every manifest kernel (analysis/kernelcheck) — a
backend-less round still certifies that the verify plane's shapes,
dtypes, and jaxpr fingerprints hold — and "shardcheck": the
sharded-plane contract pass (analysis/shardcheck) traced under a
forced 8-device CPU mesh in a subprocess, certifying shardings,
collective census, compile-cost budgets, and donation discipline —
and "rangecheck": per-kernel overflow headroom from the checked-in
range certificates (analysis/rangecheck) with a live interval
spot-check over the fast hash-plane kernels (BENCH_RANGECHECK=0
opts out, like the other two).

BENCH_WORKLOAD=multichip sweeps the same verify over device counts
(default 1/2/4/8) and reports per-count p50 scaling plus
cold-start-to-first-verify from an empty comb cache — the ROADMAP item 1
capture (see _run_multichip); BENCH_WORKLOAD=mixed drives concurrent
consensus + mempool CheckTx load through the verify service;
BENCH_WORKLOAD=bls sweeps validator-set sizes comparing ed25519-batch
vs BLS-aggregate-commit p50 and reports the crossover set size
(see _run_bls); BENCH_WORKLOAD=secp sweeps batch sizes comparing the
TPU-batched secp256k1/ECDSA lane vs the pure-host lane and drives a
mixed ed25519+secp CheckTx ingest round with per-key-type per-class
latency (see _run_secp); BENCH_WORKLOAD=proofs sweeps coalesced
Merkle-proof query counts comparing the one-dispatch TPU proof kernel
(ops/merkle.proofs_from_leaves) against the host
proofs_from_byte_slices oracle — bit-identity is asserted on every
swept size, and the multiproof shared-node dedup factor rides in the
same line (see _run_proofs).

Baseline: curve25519-voi batch verify ~27.5 us/sig/core on the QA CPUs
(BASELINE.md: 50-60 us single, ~2x batch gain) -> 275 ms for 10k sigs.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# The ONE wedge-safe device probe (subprocess + killpg + poll deadline)
# lives in the library now — the health sentinel runs it periodically on
# live nodes, this bench runs it before the expensive table build.
# utils/healthmon imports no jax, so the "jax not yet imported" contract
# the kernelcheck fallback relies on still holds.
from cometbft_tpu.utils import healthmon as _healthmon

GO_CPU_US_PER_SIG = 27.5

# The bench measures the WARM comb path; the async background build
# (crypto/batch.comb_async_min) would route the timed calls through the
# uncached fallback while tables warm — force synchronous builds.
os.environ.setdefault("COMETBFT_TPU_COMB_ASYNC_MIN", str(1 << 30))


def _probe_timeout_s() -> int:
    try:
        return int(os.environ.get("BENCH_PROBE_TIMEOUT", "240") or 240)
    except ValueError:
        return 240

REPORT: dict = {
    "metric": "verify_commit_p50_10k_ms",
    "value": None,
    "unit": "ms",
    "vs_baseline": None,
    "verifier": "comb-cached",
    "phases": {},
}


def emit_and_exit(code: int = 0) -> None:
    print(json.dumps(REPORT))
    raise SystemExit(code)


def backend_available() -> "_healthmon.ProbeResult":
    """Probe the accelerator backend via the SHARED hang-proof probe
    (cometbft_tpu/utils/healthmon.probe_devices): `jax.devices()` in a
    throwaway subprocess of its own session, SIGKILLed (whole group) at
    the BENCH_PROBE_TIMEOUT deadline — the same implementation the node
    health sentinel runs periodically, so a wedge seen here and a wedge
    seen by /tpu_health are the same measurement."""
    return _healthmon.probe_devices(_probe_timeout_s())


def _arm_run_watchdog() -> None:
    """Guarantee ONE structured JSON line even if the run wedges AFTER
    the probe passed (the tunnel can die mid-benchmark: three driver
    rounds recorded null artifacts from exactly that).  A daemon timer
    prints the report with an error and hard-exits; BENCH_HARD_TIMEOUT
    seconds, default 2400 (enough for a cold 10k table build + 12 timed
    iterations over the tunnel), 0 disables."""
    import threading

    try:
        budget = int(os.environ.get("BENCH_HARD_TIMEOUT", "2400") or 0)
    except ValueError:
        budget = 2400
    if budget <= 0:
        return

    def fire():
        REPORT["error"] = f"bench wedged: no result within {budget}s"
        print(json.dumps(REPORT), flush=True)
        os._exit(0)

    t = threading.Timer(budget, fire)
    t.daemon = True
    t.start()


def probe_backend() -> bool:
    """Probe the backend; True = healthy.  On a dead backend the round
    is no longer lost: with the failover plane armed
    (COMETBFT_TPU_FAILOVER, default on — the same knob the verify
    service trips on) the bench falls back to a DEGRADED round
    (_run_degraded: the service's CPU-fallback path, labeled
    backend_mode=cpu_fallback) instead of emitting only an error object
    the way BENCH r03-r05 did.  With failover disabled, the old
    fail-fast behavior: emit the structured error line and exit.

    A wedged tunnel often recovers when a stranded client's lease
    expires, so a failed probe retries a few times (BENCH_PROBE_RETRIES,
    default 2, BENCH_PROBE_RETRY_DELAY, default 90 s apart) before
    giving up — cheap insurance against reporting value=null for a
    transient wedge.  The defaults deliberately keep the worst case
    (attempts x probe timeout + sleeps) under ~10 minutes; see the
    budget note below before changing either."""
    if os.environ.get("BENCH_SKIP_PROBE") == "1":
        return True

    def _int_env(name: str, default: int) -> int:
        try:
            return int(os.environ.get(name, "") or default)
        except ValueError:
            return default

    # defaults keep the worst case (attempts x probe timeout + sleeps)
    # under ~10 min — the driver tolerated >4 min probe hangs in past
    # rounds, but a structured line must still land within its patience
    attempts = max(1, _int_env("BENCH_PROBE_RETRIES", 2))
    delay_s = max(0, _int_env("BENCH_PROBE_RETRY_DELAY", 90))
    results = []
    for attempt in range(attempts):
        if attempt:
            time.sleep(delay_s)
        res = backend_available()
        results.append(res)
        if res.ok:
            REPORT["backend"] = res.detail
            REPORT["probe_attempts"] = attempt + 1
            return True
    REPORT["probe_attempts"] = attempts
    # the sentinel's structured wedge report, not a bespoke string: each
    # attempt's verdict/latency/timeout flag, in order — the same shape
    # /tpu_health serves under "last_probe" on a live node
    REPORT["wedge_report"] = {
        "state": "wedged" if results[-1].timed_out else "unavailable",
        "attempts": [r.to_dict() for r in results],
        "probe_timeout_s": _probe_timeout_s(),
    }
    # the backend is dead for this round: nothing after this point may
    # touch the tunnel.  Scrub the axon plugin trigger NOW, before the
    # in-process kernelcheck (or anything else) imports jax — cpu-pinning
    # alone is not trusted to keep plugin registration off a wedged
    # tunnel (shardcheck pops this for its child for the same reason)
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    if os.environ.get("BENCH_KERNELCHECK", "1").lower() not in (
        "0", "false", "no", "off"
    ):
        REPORT["kernelcheck"] = _kernelcheck_report()
    if os.environ.get("BENCH_SHARDCHECK", "1").lower() not in (
        "0", "false", "no", "off"
    ):
        REPORT["shardcheck"] = _shardcheck_report()
    if os.environ.get("BENCH_RANGECHECK", "1").lower() not in (
        "0", "false", "no", "off"
    ):
        REPORT["rangecheck"] = _rangecheck_report()
    from cometbft_tpu.utils import envknobs as _envknobs

    if _envknobs.get_bool(_envknobs.FAILOVER):
        # failover armed: the round degrades instead of dying — keep
        # the wedge evidence, but the line will carry a real p50
        REPORT["backend_error"] = "backend-unavailable: " + results[-1].detail
        REPORT["backend_mode"] = "cpu_fallback"
        return False
    REPORT["error"] = "backend-unavailable: " + results[-1].detail
    emit_and_exit()


def _kernelcheck_report() -> dict:
    """The CPU-only kernel contract pass (analysis/kernelcheck): traces
    every manifest kernel under JAX_PLATFORMS=cpu and diffs against the
    checked-in fingerprints.  Run when the device backend is unavailable
    (BENCH_r05: rounds that only carried an error object) so the bench
    round still reports a meaningful verify-plane signal — the kernels'
    numeric contract holding is worth recording even when their wall
    clock is unmeasurable.  ~2-3 min of CPU tracing, well inside the run
    watchdog; BENCH_KERNELCHECK=0 skips it (the bench-harness tests do,
    to stay inside their own subprocess timeout).

    jax has NOT been imported in this process yet (the probe runs in a
    throwaway subprocess), so JAX_PLATFORMS is forced to cpu HERE, before
    the first import — whatever platform the ambient environment wanted,
    this pass must never re-touch the tunnel the probe just declared
    wedged."""
    try:
        if "jax" in sys.modules:  # can't re-pin an already-initialized jax
            return {"ok": False, "error": "jax already imported pre-probe"}
        os.environ["JAX_PLATFORMS"] = "cpu"
        t0 = time.monotonic()
        from cometbft_tpu.analysis import kernelcheck

        # honor justified allowlist entries so this report agrees with
        # `scripts/lint.py --check kernel` on what counts as green
        findings, traces = kernelcheck.run_check(
            allowlist=kernelcheck.default_allowlist()
        )
        return {
            **kernelcheck.summary(findings, traces),
            "elapsed_s": round(time.monotonic() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 — the JSON line must still emit
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def _shardcheck_report() -> dict:
    """The sharded-plane contract pass (analysis/shardcheck): every
    mesh-parameterized kernel traced under a REAL 8-way CPU mesh and
    held to its declared shardings, collective census, compile-cost
    budgets, and donation discipline — so a wedged-tunnel round
    (MULTICHIP/backend-less) still carries sharded-plane signal, the
    same pattern as the "kernelcheck" field above.  Runs entirely in a
    forced-environment SUBPROCESS (JAX_PLATFORMS=cpu +
    xla_force_host_platform_device_count=8 exported before the child's
    first jax import), so this process's jax state and the wedged
    tunnel are both untouched.  ~40s; the child timeout is capped at
    300s so that probe retries + kernelcheck + this pass still land the
    structured JSON line inside the driver's patience — a hung trace
    child becomes a timeout finding in the summary, not a lost round.
    BENCH_SHARDCHECK=0 skips it (the bench-harness tests do, to stay
    inside their subprocess timeout)."""
    try:
        t0 = time.monotonic()
        from cometbft_tpu.analysis import kernelcheck, shardcheck

        findings, data = shardcheck.run_subprocess(timeout=300)
        allow = kernelcheck.default_allowlist()
        findings = [f for f in findings if not allow.suppresses(f)]
        censuses = {
            name: k.get("collectives", {})
            for name, k in data.get("kernels", {}).items()
        }
        return {
            "ok": not findings,
            "findings": len(findings),
            "kernels": {
                name: k.get("eqns")
                for name, k in data.get("kernels", {}).items()
            },
            # the stage-handoff claim, machine-checkable next to the
            # perf numbers: a sharding_constraint in any census is a
            # resharding copy between pipelined stages
            "collectives": censuses,
            "resharding_free": all(
                "sharding_constraint" not in c for c in censuses.values()
            ) if censuses else None,
            "device_count": data.get("device_count"),
            "elapsed_s": round(time.monotonic() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 — the JSON line must still emit
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def _rangecheck_report() -> dict:
    """The limb-range contract pass (analysis/rangecheck): per-kernel
    overflow headroom from the checked-in range certificates, plus a
    live interval spot-check over the fast hash-plane kernels diffed
    against those certificates — the same wedged-round pattern as the
    "kernelcheck"/"shardcheck" fields above.  The FULL interval pass is
    minutes of CPU (the curve walks dominate), so the certificates carry
    the field-kernel headroom and the spot subset keeps the round honest
    about drift.  Runs under the cpu pin the kernelcheck report already
    forced; BENCH_RANGECHECK=0 skips it (the bench-harness tests do, to
    stay inside their subprocess timeout)."""
    try:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        t0 = time.monotonic()
        from cometbft_tpu.analysis import rangecheck

        return {
            **rangecheck.bench_summary(),
            "elapsed_s": round(time.monotonic() - t0, 1),
        }
    except BaseException as e:  # noqa: BLE001 — the JSON line must still emit
        return {"ok": False, "error": f"{type(e).__name__}: {e}"}


def _enable_compile_cache() -> None:
    """Persistent XLA compile cache: the knob-driven helper
    (utils/compilecache, COMETBFT_TPU_COMPILE_CACHE), defaulting to the
    driver's shared tests/.jax_cache dir like
    __graft_entry__._enable_compile_cache — the comb table-build program
    is tens of seconds of TPU compile; with the cache warm,
    table_build_s is the arithmetic only."""
    try:
        from cometbft_tpu.utils import compilecache

        compilecache.maybe_enable(
            default_dir=os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                "tests", ".jax_cache",
            )
        )
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass


def _run_mixed() -> None:
    """BENCH_WORKLOAD=mixed: consensus commit verification and mempool
    CheckTx signature checks driven CONCURRENTLY through the unified
    verify service (verifysvc/), to show the scheduler's class
    separation under contention.  The JSON line carries per-class
    latency percentiles plus the service's flush/queue tallies — the
    claim to check is that consensus p50 under mempool load stays near
    its unloaded value while mempool requests coalesce into wide
    deadline-flushed batches.

    Sizes: BENCH_N commit signatures (default 10000), BENCH_MIXED_SECONDS
    of concurrent load (default 20), BENCH_MIXED_SENDERS CheckTx threads
    (default 8)."""
    import threading

    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.utils import heightline
    from cometbft_tpu.verifysvc import checktx
    from cometbft_tpu.verifysvc.service import global_service

    N = int(os.environ.get("BENCH_N", "10000"))
    seconds = float(os.environ.get("BENCH_MIXED_SECONDS", "20"))
    senders = int(os.environ.get("BENCH_MIXED_SENDERS", "8"))
    REPORT["metric"] = "verify_mixed_consensus_p50_ms"
    REPORT["workload"] = "mixed"
    REPORT["n_sigs"] = N
    REPORT["mixed_seconds"] = seconds
    REPORT["mixed_senders"] = senders

    rng = np.random.default_rng(11)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    pubs = [k.pub_key().data for k in keys]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-mixed"
        items.append((pubs[i], msg, sk.sign(msg)))

    t0 = time.perf_counter()
    crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
    REPORT["phases"]["table_build_s"] = round(time.perf_counter() - t0, 1)

    tx_keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(64)]
    txs = [
        checktx.make_signed_tx(k, b"mixed-payload-%d" % i)
        for i, k in enumerate(tx_keys)
    ]

    stop = threading.Event()
    lat: dict[str, list[float]] = {"consensus": [], "mempool": []}
    lat_mtx = threading.Lock()
    errors: list[str] = []

    # each consensus round below is one synthetic "height": the bench
    # surfaces the same per-height ledger a node serves on
    # /height_timeline, with the commit verify attributed per height
    hl = heightline.HeightlineRegistry(capacity=128, enabled=True)

    def consensus_loop():
        try:
            height = 0
            while not stop.is_set():
                height += 1
                hl.set_current(height)
                hl.mark(height, "start", _record=False)
                v = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
                t = time.perf_counter()
                for pub, msg, sig in items:
                    v.add(pub, msg, sig)
                ok, per = v.verify()
                dt = (time.perf_counter() - t) * 1e3
                assert ok and len(per) == N
                hl.mark(height, "commit", _record=False)
                hl.note_verify(N, dt / 1e3, height=height)
                with lat_mtx:
                    lat["consensus"].append(dt)
        except BaseException as e:  # noqa: BLE001 — report, don't hang the bench
            errors.append(f"consensus: {type(e).__name__}: {e}")
            stop.set()

    def mempool_loop(i: int):
        try:
            j = i
            while not stop.is_set():
                t = time.perf_counter()
                ok = checktx.verify_tx_signature(txs[j % len(txs)])
                dt = (time.perf_counter() - t) * 1e3
                assert ok is True
                with lat_mtx:
                    lat["mempool"].append(dt)
                j += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(f"mempool-{i}: {type(e).__name__}: {e}")
            stop.set()

    threads = [threading.Thread(target=consensus_loop, name="bench-consensus")]
    threads += [
        threading.Thread(target=mempool_loop, args=(i,), name=f"bench-mempool-{i}")
        for i in range(senders)
    ]
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=120)

    def pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 3)

    stats = global_service().stats()
    REPORT["value"] = pct(lat["consensus"], 0.5)
    REPORT["classes"] = {
        k: {
            "count": len(v),
            "p50_ms": pct(v, 0.5),
            "p95_ms": pct(v, 0.95),
        }
        for k, v in lat.items()
    }
    REPORT["scheduler"] = {
        "dispatched_batches": stats["dispatched_batches"],
        "rejected": stats["rejected"],
        "batch_max": stats["batch_max"],
        "deadline_ms": stats["deadline_ms"],
    }
    snap = hl.snapshot(limit=10)
    REPORT["height_timeline"] = {
        "heights_total": hl.current,
        "newest": [
            {
                "height": h["height"],
                "commit_s": h["phase_seconds"].get("commit"),
                "verify": h["verify"],
            }
            for h in snap["heights"]
        ],
    }
    if errors:
        REPORT["error"] = "; ".join(errors[:4])
    emit_and_exit()


def _run_bls() -> None:
    """BENCH_WORKLOAD=bls: the ed25519-vs-BLS cost-model crossover
    capture (ROADMAP item 2 / PAPERS.md arXiv:2302.00418).  Sweeps
    validator-set sizes (BENCH_BLS_SIZES, default 64,256,1024,4096)
    and measures, per size:

      * ed25519-batch: N individually signed rows through the
        production batch path (crypto/batch.create_batch_verifier,
        comb-cached) — cost grows ~linearly in N;
      * BLS-aggregate: ONE aggregate commit (N validators, one shared
        message, one aggregate G2 signature replicated per row) through
        the BLS lane (models/bls_verifier behind the verify service) —
        one pairing-product check plus a data-parallel pubkey sum, so
        cost is ~flat in N once the validated-pubkey cache is warm
        (steady state: the validator set outlives the commit, exactly
        like the resident ed25519 comb tables).

    The JSON line carries per-size p50 for both schemes and
    ``crossover_validators``: the interpolated set size where the BLS
    aggregate becomes cheaper than the ed25519 batch (null when the
    sweep never crosses).  Setup uses small secret scalars (pk/sig
    scalar mults dominate setup wall clock; verification cost is
    independent of scalar size), distinct per validator.
    """
    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import bls12381 as host_bls
    from cometbft_tpu.crypto import ed25519 as host_ed
    from cometbft_tpu.models import bls_verifier

    sizes = [
        int(x) for x in
        os.environ.get("BENCH_BLS_SIZES", "64,256,1024,4096").split(",")
        if x.strip()
    ]
    iters = int(os.environ.get("BENCH_BLS_ITERS", "5"))
    REPORT["metric"] = "verify_bls_crossover_validators"
    REPORT["workload"] = "bls"
    REPORT["sizes"] = sizes
    REPORT["iters"] = iters

    rng = np.random.default_rng(17)

    def p50(fn):
        runs = sorted(fn() for _ in range(iters))
        return runs[len(runs) // 2]

    sweep: dict[str, dict] = {}
    n_max = max(sizes)
    # one key universe per scheme, sliced per size (setup dominates the
    # sweep's wall clock; the timed regions only ever see warm caches)
    ed_keys = [host_ed.PrivKey.from_seed(rng.bytes(32)) for _ in range(n_max)]
    ed_pubs = [k.pub_key().data for k in ed_keys]
    # distinct small scalars: verification cost is scalar-size-blind
    sks = rng.choice(1 << 30, size=n_max, replace=False) + 2
    bls_keys = [host_bls.PrivKey(int(sk)) for sk in sks]
    bls_pubs = [k.pub_key().data for k in bls_keys]

    for n in sizes:
        row: dict = {}
        # ---- ed25519 batch: N rows, per-validator sign bytes
        pubs = ed_pubs[:n]
        items = []
        for i, sk in enumerate(ed_keys[:n]):
            msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-bls-bench"
            items.append((pubs[i], msg, sk.sign(msg)))
        crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)  # warm tables

        def run_ed():
            v = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
            t0 = time.perf_counter()
            for pub, msg, sig in items:
                v.add(pub, msg, sig)
            ok, per = v.verify()
            dt = (time.perf_counter() - t0) * 1e3
            assert ok and len(per) == n
            return dt

        run_ed()  # warmup (bucket compile / cache warm)
        row["ed25519_p50_ms"] = round(p50(run_ed), 3)

        # ---- BLS aggregate commit: one message, one aggregate sig
        msg = b"\x08\x02\x10\x01\x18\x05|bls-agg-commit|%d" % n
        agg_sig = host_bls.aggregate_signatures(
            [k.sign(msg) for k in bls_keys[:n]]
        )
        bpubs = bls_pubs[:n]

        def run_bls():
            v = crypto_batch.create_batch_verifier("bls12_381", pubkeys=bpubs)
            t0 = time.perf_counter()
            for pub in bpubs:
                v.add(pub, msg, agg_sig)
            ok, per = v.verify()
            dt = (time.perf_counter() - t0) * 1e3
            assert ok and len(per) == n
            return dt

        # genuinely cold first verify per size: the key universe is
        # sliced, so without the reset the n=256 round would find the
        # first 64 keys already validated by the n=64 round
        bls_verifier.reset_caches()
        t0 = time.perf_counter()
        run_bls()  # warmup: pays pubkey validation once (cache fill)
        row["bls_first_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        row["bls_p50_ms"] = round(p50(run_bls), 3)
        sweep[str(n)] = row

    REPORT["sweep"] = sweep

    # crossover: smallest swept size where the aggregate wins, with a
    # log-linear interpolation between the straddling sizes
    crossover = None
    prev = None
    for n in sizes:
        row = sweep[str(n)]
        d = row["bls_p50_ms"] - row["ed25519_p50_ms"]
        if d <= 0:
            if prev is None:
                crossover = n
            else:
                pn, pd = prev
                # linear interpolation of the (bls - ed) gap in log2(N)
                import math

                f = pd / (pd - d) if pd != d else 0.0
                crossover = int(round(
                    2 ** (math.log2(pn) + f * (math.log2(n) - math.log2(pn)))
                ))
            break
        prev = (n, d)
    REPORT["value"] = REPORT["crossover_validators"] = crossover
    REPORT["unit"] = "validators"
    emit_and_exit()


def _run_secp() -> None:
    """BENCH_WORKLOAD=secp: the batched-ECDSA capture of ROADMAP item 4
    (PAPERS.md arXiv:2112.02229).  Two measurements in one JSON line:

    * **batch-size sweep** (BENCH_SECP_SIZES, default 64,256,1024,4096):
      per size, p50 of the TPU-batched lane (models/secp_verifier ->
      ops/secp256k1: range checks, Montgomery batch inversion, Shamir
      double-scalar — one fused dispatch) vs the pure-host ECDSA lane.
      The host path is pure-Python bigint ECDSA (~tens of ms per
      signature), so it is measured on min(n, BENCH_SECP_HOST_CAP
      [default 64]) rows and reported per-signature plus extrapolated
      (``host_extrapolated`` carries the flag AND the cap AND the
      measured-subset size — an extrapolated number is never passed
      off as a measured one, and the JSON line alone says how much was
      actually measured).
    * **phase attribution** (BENCH_SECP_PHASES=0 to skip): the
      top-size dispatch split into hash / decode / assembly / h2d /
      kernel / fetch (models/secp_verifier.LAST_PHASES), captured for
      the default shape (GLV + fused on-device hashing) AND the PR-15
      witness (noglv + host hashing, BENCH_SECP_PHASE_WITNESS=0 to
      skip its extra compile) — the GLV and hashing-residency deltas
      ride in the same JSON line as the sweep.
    * **mixed ingest round** (BENCH_SECP_MIXED_SECONDS, default 10):
      concurrent ed25519-commit consensus load plus TWO mempool CheckTx
      sender pools — ed25519 (v1 envelopes, MODE_PLAIN) and secp256k1
      (key-typed v2 envelopes, MODE_SECP) — through one verify service,
      reporting per-key-type per-class latency percentiles: the
      Ethereum-shaped ingest claim next to the scheduler's class
      separation.
    """
    import threading

    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host_ed
    from cometbft_tpu.crypto import secp256k1 as host_secp
    from cometbft_tpu.models import secp_verifier as mv
    from cometbft_tpu.verifysvc import checktx
    from cometbft_tpu.verifysvc.service import global_service

    sizes = [
        int(x) for x in
        os.environ.get("BENCH_SECP_SIZES", "64,256,1024,4096").split(",")
        if x.strip()
    ]
    iters = int(os.environ.get("BENCH_SECP_ITERS", "5"))
    host_cap = int(os.environ.get("BENCH_SECP_HOST_CAP", "64"))
    REPORT["metric"] = "verify_secp_tpu_batch_p50_ms"
    REPORT["workload"] = "secp"
    REPORT["verifier"] = "secp-batched"
    REPORT["sizes"] = sizes
    REPORT["iters"] = iters

    rng = np.random.default_rng(23)
    n_max = max(sizes)
    keys = [host_secp.PrivKey.from_seed(rng.bytes(32)) for _ in range(n_max)]
    pubs = [k.pub_key().data for k in keys]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-secp"
        items.append((pubs[i], msg, sk.sign(msg)))

    def p50(fn):
        runs = sorted(fn() for _ in range(iters))
        return runs[len(runs) // 2]

    sweep: dict[str, dict] = {}
    for n in sizes:
        row: dict = {}
        batch = items[:n]

        def run_tpu(batch=batch, n=n):
            v = mv.TpuSecpBatchVerifier()
            t0 = time.perf_counter()
            for it in batch:
                v.add(*it)
            ok, per = v.verify()
            dt = (time.perf_counter() - t0) * 1e3
            assert ok and len(per) == n
            return dt

        run_tpu()  # warmup: bucket-shape compile / cache hit
        row["tpu_p50_ms"] = round(p50(run_tpu), 3)

        hn = min(n, host_cap)
        hbatch = batch[:hn]

        def run_host(hbatch=hbatch, hn=hn):
            v = mv.CpuSecpBatchVerifier()
            t0 = time.perf_counter()
            for it in hbatch:
                v.add(*it)
            ok, per = v.verify()
            dt = (time.perf_counter() - t0) * 1e3
            assert ok and len(per) == hn
            return dt

        host_ms = p50(run_host)
        row["host_p50_ms_per_sig"] = round(host_ms / hn, 3)
        row["host_p50_ms"] = round(
            host_ms if hn == n else host_ms / hn * n, 3
        )
        row["host_extrapolated"] = {
            "extrapolated": hn != n,
            "cap": host_cap,
            "measured_rows": hn,
        }
        row["tpu_speedup_vs_host"] = round(
            row["host_p50_ms"] / row["tpu_p50_ms"], 2
        ) if row["tpu_p50_ms"] else None
        sweep[str(n)] = row
    REPORT["sweep"] = sweep
    top = sweep[str(max(sizes))]
    REPORT["value"] = top["tpu_p50_ms"]

    # ---- phase attribution of the top-size dispatch: default shape
    # (GLV + fused hashing) vs the PR-15 witness (noglv + host
    # hashing) — the same LAST_PHASES capture scripts/
    # profile_secp_phases.py prints, embedded in the JSON line
    if os.environ.get("BENCH_SECP_PHASES", "1") != "0":
        import statistics

        phase_keys = ("hash_ms", "decode_ms", "assembly_ms",
                      "h2d_ms", "kernel_ms", "fetch_ms")
        cfgs: dict[str, dict[str, str]] = {"glv_fused": {}}
        if os.environ.get("BENCH_SECP_PHASE_WITNESS", "1") != "0":
            cfgs["noglv_host"] = {
                "COMETBFT_TPU_SECP_GLV": "0",
                "COMETBFT_TPU_SECP_HASH_DEVICE_MIN": "0",
            }
        pbatch = items[:max(sizes)]
        attribution: dict[str, dict] = {}
        for cname, cenv in cfgs.items():
            saved = {k: os.environ.get(k) for k in cenv}
            os.environ.update(cenv)
            try:
                mv._verify_items(pbatch, use_device=True)  # warm variant
                samples: dict[str, list[float]] = {k: [] for k in phase_keys}
                walls = []
                for _ in range(iters):
                    t0 = time.perf_counter()
                    mv._verify_items(pbatch, use_device=True)
                    walls.append((time.perf_counter() - t0) * 1e3)
                    for k in phase_keys:
                        samples[k].append(mv.LAST_PHASES.get(k, 0.0))
                wall = statistics.median(walls)
                attribution[cname] = {
                    "wall_p50_ms": round(wall, 3),
                    "hash_device": bool(mv.LAST_PHASES.get("hash_device")),
                    **{k: {
                        "p50_ms": round(statistics.median(samples[k]), 3),
                        "share_of_wall": round(
                            statistics.median(samples[k]) / wall, 3
                        ) if wall else 0.0,
                    } for k in phase_keys},
                }
            finally:
                for k, old in saved.items():
                    if old is None:
                        os.environ.pop(k, None)
                    else:
                        os.environ[k] = old
        REPORT["phase_attribution"] = attribution

    # ---- mixed ed25519 + secp256k1 ingest round
    seconds = float(os.environ.get("BENCH_SECP_MIXED_SECONDS", "10"))
    senders = int(os.environ.get("BENCH_SECP_MIXED_SENDERS", "4"))
    n_commit = int(os.environ.get("BENCH_SECP_COMMIT_N", "1000"))
    ed_keys = [host_ed.PrivKey.from_seed(rng.bytes(32)) for _ in range(n_commit)]
    ed_pubs = [k.pub_key().data for k in ed_keys]
    commit_items = []
    for i, sk in enumerate(ed_keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|mixed-commit"
        commit_items.append((ed_pubs[i], msg, sk.sign(msg)))
    crypto_batch.create_batch_verifier("ed25519", pubkeys=ed_pubs)

    ed_txs = [
        checktx.make_signed_tx(host_ed.PrivKey.from_seed(rng.bytes(32)),
                               b"mixed-ed-%d" % i)
        for i in range(32)
    ]
    secp_txs = [
        checktx.make_signed_tx(host_secp.PrivKey.from_seed(rng.bytes(32)),
                               b"mixed-secp-%d" % i)
        for i in range(32)
    ]

    stop = threading.Event()
    lat: dict[str, list[float]] = {
        "consensus_ed25519": [], "mempool_ed25519": [], "mempool_secp256k1": [],
    }
    lat_mtx = threading.Lock()
    errors: list[str] = []

    def consensus_loop():
        try:
            while not stop.is_set():
                v = crypto_batch.create_batch_verifier("ed25519", pubkeys=ed_pubs)
                t = time.perf_counter()
                for it in commit_items:
                    v.add(*it)
                ok, per = v.verify()
                dt = (time.perf_counter() - t) * 1e3
                assert ok and len(per) == n_commit
                with lat_mtx:
                    lat["consensus_ed25519"].append(dt)
        except BaseException as e:  # noqa: BLE001 — report, don't hang the bench
            errors.append(f"consensus: {type(e).__name__}: {e}")
            stop.set()

    def mempool_loop(i: int, txs, key):
        try:
            j = i
            while not stop.is_set():
                t = time.perf_counter()
                ok = checktx.verify_tx_signature(txs[j % len(txs)])
                dt = (time.perf_counter() - t) * 1e3
                assert ok is True
                with lat_mtx:
                    lat[key].append(dt)
                j += 1
        except BaseException as e:  # noqa: BLE001
            errors.append(f"{key}-{i}: {type(e).__name__}: {e}")
            stop.set()

    threads = [threading.Thread(target=consensus_loop, name="bench-consensus")]
    threads += [
        threading.Thread(target=mempool_loop, args=(i, ed_txs, "mempool_ed25519"),
                         name=f"bench-mp-ed-{i}")
        for i in range(senders)
    ]
    threads += [
        threading.Thread(
            target=mempool_loop, args=(i, secp_txs, "mempool_secp256k1"),
            name=f"bench-mp-secp-{i}")
        for i in range(senders)
    ]
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=120)

    def pct(vals, q):
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 3)

    stats = global_service().stats()
    REPORT["mixed"] = {
        "seconds": seconds,
        "senders_per_key_type": senders,
        "commit_n": n_commit,
        "classes": {
            k: {"count": len(v), "p50_ms": pct(v, 0.5), "p95_ms": pct(v, 0.95)}
            for k, v in lat.items()
        },
        "scheduler": {
            "dispatched_batches": stats["dispatched_batches"],
            "rejected": stats["rejected"],
        },
    }
    if errors:
        REPORT["error"] = "; ".join(errors[:4])
    emit_and_exit()


def _run_proofs() -> None:
    """BENCH_WORKLOAD=proofs: the TPU proof-serving-plane capture.
    Sweeps coalesced query counts (BENCH_PROOF_SIZES, default
    64,256,1024,4096 — the top size is the >=1k-coalesced-queries
    acceptance point) and measures, per count K over a K-leaf tree:

      * tpu: crypto/merkle.device_proofs_from_byte_slices — host plans
        sibling coordinates, ONE device dispatch retains every interior
        level and one-hot-gathers all K audit paths;
      * host: crypto/merkle.proofs_from_byte_slices — the pure-host
        oracle that DEFINES the proof bytes (every degraded service
        route funnels to it).

    Bit-identity between the two is asserted on every swept size — a
    fast proof plane that serves different bytes is a bug, not a win —
    and each row carries the multiproof shared-node dedup factor
    (crypto/merkle.multiproof_plan: naive path-node slots over deduped
    unique nodes) for the same K.  p50 AND p95 ride per lane: proof
    fan-out is a latency-sensitive read path, so the tail is part of
    the claim."""
    from cometbft_tpu.crypto import merkle as cmerkle

    sizes = [
        int(x) for x in
        os.environ.get("BENCH_PROOF_SIZES", "64,256,1024,4096").split(",")
        if x.strip()
    ]
    iters = int(os.environ.get("BENCH_PROOF_ITERS", "5"))
    REPORT["metric"] = "proof_gen_tpu_batch_p50_ms"
    REPORT["workload"] = "proofs"
    REPORT["verifier"] = "merkle-proof-batched"
    REPORT["sizes"] = sizes
    REPORT["iters"] = iters

    def pct(vals, q):
        s = sorted(vals)
        return round(s[min(len(s) - 1, int(q * len(s)))], 3)

    rng = np.random.default_rng(29)
    sweep: dict[str, dict] = {}
    for n in sizes:
        row: dict = {}
        leaves = [rng.bytes(64) for _ in range(n)]
        idxs = list(range(n))  # every leaf queried: worst-case coalesce

        def run_tpu(leaves=leaves, idxs=idxs):
            t0 = time.perf_counter()
            root, proofs = cmerkle.device_proofs_from_byte_slices(leaves, idxs)
            dt = (time.perf_counter() - t0) * 1e3
            assert len(proofs) == len(idxs)
            return dt, root, proofs

        def run_host(leaves=leaves, idxs=idxs):
            t0 = time.perf_counter()
            root, all_proofs = cmerkle.proofs_from_byte_slices(leaves)
            proofs = [all_proofs[i] for i in idxs]
            dt = (time.perf_counter() - t0) * 1e3
            return dt, root, proofs

        _, d_root, d_proofs = run_tpu()  # warmup: shape compile / cache hit
        _, h_root, h_proofs = run_host()
        # the contract, asserted in the bench itself: same root, same
        # proof bytes, row for row
        assert d_root == h_root
        assert all(
            dp.total == hp.total and dp.index == hp.index
            and dp.leaf_hash == hp.leaf_hash and dp.aunts == hp.aunts
            for dp, hp in zip(d_proofs, h_proofs)
        ), f"device/host proof divergence at n={n}"

        tpu_runs = [run_tpu()[0] for _ in range(iters)]
        host_runs = [run_host()[0] for _ in range(iters)]
        row["tpu_p50_ms"] = pct(tpu_runs, 0.5)
        row["tpu_p95_ms"] = pct(tpu_runs, 0.95)
        row["host_p50_ms"] = pct(host_runs, 0.5)
        row["host_p95_ms"] = pct(host_runs, 0.95)
        row["tpu_speedup_vs_host"] = round(
            row["host_p50_ms"] / row["tpu_p50_ms"], 2
        ) if row["tpu_p50_ms"] else None
        _, _, coords, naive = cmerkle.multiproof_plan(n, idxs)
        row["multiproof_dedup_factor"] = round(
            naive / len(coords), 2
        ) if coords else None
        row["bit_identical"] = True  # the asserts above did not fire
        sweep[str(n)] = row
    REPORT["sweep"] = sweep
    top = sweep[str(max(sizes))]
    REPORT["value"] = top["tpu_p50_ms"]
    REPORT["unit"] = "ms"
    emit_and_exit()


def _run_multichip() -> None:
    """BENCH_WORKLOAD=multichip: the 8-device scaling capture of ROADMAP
    item 1.  Sweeps the comb-cached commit verify over device counts
    (BENCH_MULTICHIP_DEVICES, default "1,2,4,8", clamped to what the
    host exposes) and reports, per count:

      - p50 of the warm verify (BENCH_MULTICHIP_ITERS, default 5), and
      - COLD-START-TO-FIRST-VERIFY: wall clock from an EMPTY comb cache
        to the first completed verify — table build (host-precomputed
        under COMB_HOST_BUILD_MAX, jitted beyond) + sharded placement +
        program compile-or-cache-hit + dispatch + fetch.  With the
        persistent XLA compile cache warm this is the <30s ROADMAP
        target; the pre-PR-11 table build alone compiled for 2m34s.

    BENCH_MULTICHIP_CPU=1 forces a virtual CPU mesh (the dryrun's
    _force_cpu_mesh pattern) so backend-less hosts can run the sweep —
    pair it with BENCH_SKIP_PROBE=1.  The JSON line also embeds the
    shardcheck collective censuses ("shardcheck.resharding_free") so
    the no-inter-stage-resharding claim rides next to the numbers.
    """
    N = int(os.environ.get("BENCH_N", "10000"))
    iters = int(os.environ.get("BENCH_MULTICHIP_ITERS", "5"))
    want = [
        int(x) for x in
        os.environ.get("BENCH_MULTICHIP_DEVICES", "1,2,4,8").split(",")
        if x.strip()
    ]
    if os.environ.get("BENCH_MULTICHIP_CPU") == "1":
        # a CPU-forced sweep must never touch the device tunnel: scrub
        # the axon plugin trigger BEFORE the first jax import (the probe
        # only scrubs it on its own failure branch, and BENCH_SKIP_PROBE
        # pairings bypass the probe entirely) — cpu-pinning alone is not
        # trusted to keep plugin registration off a wedged tunnel
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={max(want)}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    have = len(jax.devices())
    devices = [d for d in want if d <= have]
    REPORT["metric"] = f"verify_commit_multichip_p50_{N}_ms"
    REPORT["workload"] = "multichip"
    REPORT["n_sigs"] = N
    REPORT["device_counts"] = devices

    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.models import comb_verifier as cv
    from cometbft_tpu.parallel import make_mesh

    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    pubs = [k.pub_key().data for k in keys]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-mc"
        items.append((pubs[i], msg, sk.sign(msg)))

    def one_verify(entry):
        bv = cv.CombBatchVerifier(entry)
        t0 = time.perf_counter()
        for pub, msg, sig in items:
            bv.add(pub, msg, sig)
        ok, per = bv.verify()
        dt = (time.perf_counter() - t0) * 1e3
        assert ok and len(per) == N
        return dt, getattr(bv, "last_timings", {})

    from cometbft_tpu.ops import comb as comb_ops

    scaling: dict[str, dict] = {}
    try:
        for d in devices:
            cv.set_active_mesh(make_mesh(d) if d > 1 else None)
            cache = cv.ValsetCombCache()
            # per-count cold start must be COLD: drop the process-global
            # comb state the previous count warmed (the jitted build's
            # traced wrapper, the 24 MB basepoint constant) so every row
            # pays its own trace + table construction and rows are
            # comparable — only the PERSISTENT compile cache stays warm,
            # which is exactly the warm-pod-restart scenario the <30s
            # target is stated against
            comb_ops._BUILD_A_JIT = None
            comb_ops._B_TABLES = None
            t0 = time.perf_counter()
            entry = cache.ensure(pubs)  # EMPTY cache: the real cold start
            build_s = time.perf_counter() - t0
            first_ms, _ = one_verify(entry)  # first verify pays the compile
            cold_s = time.perf_counter() - t0
            runs = sorted(one_verify(entry) for _ in range(iters))
            p50, timings = runs[len(runs) // 2]
            scaling[str(d)] = {
                "p50_ms": round(p50, 3),
                "cold_start_to_first_verify_s": round(cold_s, 1),
                "table_build_s": round(build_s, 1),
                "first_verify_ms": round(first_ms, 3),
                "phases": {k: round(v, 2) for k, v in timings.items()},
            }
    finally:
        cv.set_active_mesh(None)

    REPORT["scaling"] = scaling
    top = scaling.get(str(devices[-1])) if devices else None
    if top:
        REPORT["value"] = top["p50_ms"]
        REPORT["vs_baseline"] = round(
            GO_CPU_US_PER_SIG * N / 1e3 / top["p50_ms"], 2
        )
        REPORT["phases"]["table_build_s"] = top["table_build_s"]
        base = scaling.get(str(devices[0]))
        if base and len(devices) > 1:
            # keyed by the ACTUAL base count — a sweep starting at 2
            # devices must not label its ratios "vs_1dev"
            REPORT[f"speedup_vs_{devices[0]}dev"] = {
                k: round(base["p50_ms"] / v["p50_ms"], 2)
                for k, v in scaling.items()
                if v["p50_ms"]
            }
    if os.environ.get("BENCH_SHARDCHECK", "1").lower() not in (
        "0", "false", "no", "off"
    ):
        REPORT["shardcheck"] = _shardcheck_report()
    emit_and_exit()


def _run_degraded() -> None:
    """Degraded-mode round: the backend probe failed but failover is
    armed, so measure what the verify service ACTUALLY serves in that
    state — batches dispatched through a tripped VerifyService onto the
    host path — and emit a real p50 labeled ``backend_mode:
    cpu_fallback``, so the perf trajectory records degraded throughput
    instead of losing the round to the tunnel (BENCH r03-r05).

    The host path is sequential per-signature verification (pure-Python
    ed25519 in this container, ~4 ms/sig), so the degraded round runs at
    reduced scale: BENCH_DEGRADED_N (default min(BENCH_N, 1000))
    signatures, BENCH_DEGRADED_ITERS (default 3) timed iterations — the
    metric name carries the N so off-scale values are never compared
    against full-scale TPU rounds.  The measurement itself never
    imports jax; if the kernelcheck pass imported it earlier, that was
    cpu-pinned with PALLAS_AXON_POOL_IPS already scrubbed (probe_backend
    failure branch), so the wedged tunnel is never re-touched either
    way."""
    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.verifysvc.client import ServiceBatchVerifier
    from cometbft_tpu.verifysvc.service import Klass, VerifyService

    N = int(
        os.environ.get("BENCH_DEGRADED_N", "")
        or min(int(os.environ.get("BENCH_N", "10000")), 1000)
    )
    iters = int(os.environ.get("BENCH_DEGRADED_ITERS", "3"))
    REPORT["metric"] = f"verify_commit_p50_{N}_ms"
    REPORT["n_sigs"] = N
    REPORT["verifier"] = "cpu-fallback"
    baseline_ms = GO_CPU_US_PER_SIG * N / 1e3

    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-degraded"
        items.append((sk.pub_key().data, msg, sk.sign(msg)))

    # a tripped service, never a raw CPU loop: the degraded p50 must
    # include the scheduler/queue overhead real degraded traffic pays.
    # probe_fn always fails so probation never spawns subprocesses that
    # would poke the wedged tunnel mid-measurement.
    svc = VerifyService(
        probe_fn=lambda _t: _healthmon.ProbeResult(
            False, "bench degraded round: probation suppressed", 0.0
        ),
    )
    svc._ensure_started()
    svc.trip_to_cpu("bench: backend unavailable, degraded round")

    def run_once():
        v = ServiceBatchVerifier(Klass.CONSENSUS, service=svc)
        t0 = time.perf_counter()
        for pub, msg, sig in items:
            v.add(pub, msg, sig)
        ok, per_sig = v.verify()
        dt = (time.perf_counter() - t0) * 1e3
        assert ok and len(per_sig) == N
        return dt

    run_once()  # warmup
    runs = sorted(run_once() for _ in range(iters))
    p50 = runs[len(runs) // 2]
    REPORT["value"] = round(p50, 3)
    REPORT["vs_baseline"] = round(baseline_ms / p50, 2)
    st = svc.stats()
    REPORT["scheduler"] = {
        "backend_mode": st["backend_mode"],
        "failover_trips": st["failover"]["trips"],
        "dispatched_batches": st["dispatched_batches"],
    }
    svc.stop()
    emit_and_exit()


def main() -> None:
    _arm_run_watchdog()
    backend_ok = probe_backend()

    if not backend_ok:
        # BEFORE the compile-cache helper: the degraded round needs no
        # compile cache (host path), and the helper's jax import should
        # not run at all when the round never touches a device
        _run_degraded()
    _enable_compile_cache()

    if os.environ.get("BENCH_WORKLOAD", "") == "mixed":
        _run_mixed()
    if os.environ.get("BENCH_WORKLOAD", "") == "multichip":
        _run_multichip()
    if os.environ.get("BENCH_WORKLOAD", "") == "bls":
        _run_bls()
    if os.environ.get("BENCH_WORKLOAD", "") == "secp":
        _run_secp()
    if os.environ.get("BENCH_WORKLOAD", "") == "proofs":
        _run_proofs()

    N = int(os.environ.get("BENCH_N", "10000"))
    warmup = int(os.environ.get("BENCH_WARMUP", "2"))
    iters = int(os.environ.get("BENCH_ITERS", "10"))
    baseline_ms = GO_CPU_US_PER_SIG * N / 1e3
    if N != 10_000:  # don't mislabel off-scale smoke runs
        REPORT["metric"] = f"verify_commit_p50_{N}_ms"
    REPORT["n_sigs"] = N

    from cometbft_tpu.crypto import batch as crypto_batch
    from cometbft_tpu.crypto import ed25519 as host

    # One validator set, one commit: distinct keys, per-validator sign-bytes.
    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    pubs = [k.pub_key().data for k in keys]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-bench"
        items.append((pubs[i], msg, sk.sign(msg)))

    # one-time per validator set: comb tables built + kept device-resident
    # (host-precomputed + device_put under COMB_HOST_BUILD_MAX, jitted
    # beyond — scripts/profile_comb_phases.py breaks the phase down)
    t0 = time.perf_counter()
    crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
    table_build_s = time.perf_counter() - t0
    REPORT["phases"]["table_build_s"] = round(table_build_s, 1)

    def run_once():
        v = crypto_batch.create_batch_verifier("ed25519", pubkeys=pubs)
        t0 = time.perf_counter()
        for pub, msg, sig in items:
            v.add(pub, msg, sig)
        ok, per_sig = v.verify()
        dt = (time.perf_counter() - t0) * 1e3
        assert ok and len(per_sig) == N
        return dt, getattr(v, "last_timings", {})

    for _ in range(warmup):
        run_once()

    # BENCH_TRACE=/path.trace.json captures the TIMED iterations with the
    # span tracer on and exports a Chrome trace (open in Perfetto) —
    # enabled only after warmup so compile/cold-cache spans neither show
    # up in the artifact nor evict timed-region events from the ring.
    trace_path = os.environ.get("BENCH_TRACE", "")
    if trace_path:
        from cometbft_tpu.utils import tracing

        tracing.set_enabled(True)
        tracing.reset()
        # traced iterations pay per-span clock reads inside the timed
        # region: flag the artifact so regression tracking never compares
        # a traced "value" against untraced baselines
        REPORT["traced"] = True

    runs = sorted((run_once() for _ in range(iters)), key=lambda r: r[0])
    p50, timings = runs[len(runs) // 2]
    REPORT["value"] = round(p50, 3)
    REPORT["vs_baseline"] = round(baseline_ms / p50, 2)
    for k, v in timings.items():
        REPORT["phases"][k] = round(v, 2)

    # Phase attribution: per-phase medians across ALL timed iterations
    # (the pipeline phases — assembly, h2d_dispatch, device_wait — run on
    # the staging thread and OVERLAP the caller-visible wall time, so
    # shares are each phase's own duration over the p50 wall clock and
    # need not sum to 1).
    phase_samples: dict[str, list[float]] = {}
    for _, t in runs:
        for k, v in t.items():
            phase_samples.setdefault(k, []).append(v)
    REPORT["phase_attribution"] = {
        k: {
            "p50_ms": round(sorted(vs)[len(vs) // 2], 3),
            "share_of_wall": round(sorted(vs)[len(vs) // 2] / p50, 3),
        }
        for k, vs in sorted(phase_samples.items())
    }
    # the cold-start cost is attributable too: one-time (per validator
    # set), so it carries no share_of_wall — amortization depends on how
    # many commits verify against the set
    REPORT["phase_attribution"]["table_build"] = {
        "p50_ms": round(table_build_s * 1e3, 1),
        "one_time": True,
    }

    if trace_path:
        REPORT["trace_events"] = tracing.export_chrome_trace(trace_path)
        REPORT["trace"] = trace_path
    emit_and_exit()


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # noqa: BLE001 — the one JSON line must emit
        REPORT["error"] = f"{type(e).__name__}: {e}"
        emit_and_exit()
