"""Benchmark of record: VerifyCommit over a 10,000-validator Commit.

Measures the full BatchVerifier path — host batch assembly (sign-bytes
digest padding) + fused TPU kernel (SHA-512 challenge, mod-L reduce,
batched double-scalar mul, cofactored check) — end to end, the same work
the reference does on CPU via curve25519-voi in verifyCommitBatch
(types/validation.go:265, crypto/ed25519/ed25519.go:220).

Prints ONE JSON line:
  {"metric": "verify_commit_p50_10k_ms", "value": <p50 ms>, "unit": "ms",
   "vs_baseline": <Go-CPU-baseline / ours, i.e. speedup>}

Baseline: curve25519-voi batch verify ≈ 27.5 µs/sig/core on the QA CPUs
(BASELINE.md: 50-60 µs single, ~2x batch gain) -> 275 ms for 10k sigs.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

N = 10_000
GO_CPU_BASELINE_MS = 275.0
WARMUP = 2
ITERS = 10


def main() -> None:
    from cometbft_tpu.crypto import ed25519 as host
    from cometbft_tpu.models.verifier import TpuEd25519BatchVerifier

    # One validator set, one commit: distinct keys, per-validator sign-bytes.
    rng = np.random.default_rng(7)
    keys = [host.PrivKey.from_seed(rng.bytes(32)) for _ in range(N)]
    items = []
    for i, sk in enumerate(keys):
        msg = b"\x08\x02\x10\x01\x18\x05" + i.to_bytes(8, "big") + b"|chain-bench"
        items.append((sk.pub_key().data, msg, sk.sign(msg)))

    def run_once() -> float:
        v = TpuEd25519BatchVerifier()
        for pub, msg, sig in items:
            v.add(pub, msg, sig)
        t0 = time.perf_counter()
        ok, per_sig = v.verify()
        dt = (time.perf_counter() - t0) * 1e3
        assert ok and len(per_sig) == N
        return dt

    for _ in range(WARMUP):
        run_once()
    times = sorted(run_once() for _ in range(ITERS))
    p50 = times[len(times) // 2]
    print(
        json.dumps(
            {
                "metric": "verify_commit_p50_10k_ms",
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(GO_CPU_BASELINE_MS / p50, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
