// Native storage engine for the block/state stores.
//
// Reference parity target: the role pebble plays in the reference
// (db/pebbledb.go — an ordered, batched, persistent KV store).  Design
// here is a single-writer log-structured store: an append-only value log
// with CRC-framed records, an in-memory ordered index (std::map) rebuilt
// from the log on open, and periodic compaction that rewrites the live
// set.  That matches this engine's actual workload — blocks and state
// snapshots are written once per height in one batch, read by key or by
// short ordered range scans, and pruned from the tail — without dragging
// in a full LSM tree.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <chrono>
#include <string>
#include <thread>
#include <vector>

namespace {

uint32_t crc32c(const uint8_t* data, size_t n) {
  // CRC-32 (Castagnoli polynomial, bitwise; cold path only)
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) {
    crc ^= data[i];
    for (int k = 0; k < 8; k++)
      crc = (crc >> 1) ^ (0x82F63B78u & (0u - (crc & 1u)));
  }
  return crc ^ 0xFFFFFFFFu;
}

struct Record {
  uint8_t type;  // 1 = set, 2 = delete
  std::string key;
  std::string value;
};

void put_u32(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>(v & 0xff));
  out.push_back(static_cast<char>((v >> 8) & 0xff));
  out.push_back(static_cast<char>((v >> 16) & 0xff));
  out.push_back(static_cast<char>((v >> 24) & 0xff));
}

uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

class KVStore {
 public:
  explicit KVStore(const std::string& path) : path_(path) {
    Load();
    CollapseFrozen();
    log_ = std::fopen(path_.c_str(), "ab");
  }

  ~KVStore() {
    if (compactor_.joinable()) compactor_.join();
    if (log_) std::fclose(log_);
  }

  bool ok() const { return log_ != nullptr; }

  void Get(const std::string& key, std::string** out) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = index_.find(key);
    *out = (it == index_.end()) ? nullptr : new std::string(it->second);
  }

  bool Has(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    return index_.count(key) != 0;
  }

  // one durable batch (fsync'd): the per-height write unit
  bool WriteBatch(const std::vector<Record>& recs) {
    std::lock_guard<std::mutex> g(mu_);
    std::string buf;
    for (const auto& r : recs) EncodeRecord(r, buf);
    if (std::fwrite(buf.data(), 1, buf.size(), log_) != buf.size()) return false;
    if (std::fflush(log_) != 0) return false;
    for (const auto& r : recs) {
      if (r.type == 1)
        index_[r.key] = r.value;
      else
        index_.erase(r.key);
      dead_ += (r.type == 2) ? 1 : 0;
    }
    writes_since_compact_ += recs.size();
    if (writes_since_compact_ > 200000 && dead_ * 4 > index_.size() &&
        !compacting_.exchange(true)) {
      FreezeLocked();
      if (compactor_.joinable()) compactor_.join();  // reap previous run
      compactor_ = std::thread([this] { CompactFrozen(); });
    }
    return true;
  }

  // ordered iteration [start, end) — collected under the lock so the
  // caller gets a stable snapshot
  void Range(const std::string& start, const std::string& end, bool reverse,
             std::vector<std::pair<std::string, std::string>>* out) {
    std::lock_guard<std::mutex> g(mu_);
    auto lo = start.empty() ? index_.begin() : index_.lower_bound(start);
    auto hi = end.empty() ? index_.end() : index_.lower_bound(end);
    for (auto it = lo; it != hi; ++it) out->push_back(*it);
    if (reverse) std::reverse(out->begin(), out->end());
  }

  size_t Size() {
    std::lock_guard<std::mutex> g(mu_);
    return index_.size();
  }

  // Compaction, writer-stall-bounded.  Phase 1 (FreezeLocked, O(1) under
  // mu_): close the active log, rename it to <path>.frozen, reopen a
  // fresh active log.  Phase 2 (CompactFrozen, NO lock held): replay the
  // frozen log, write its live set to <path>.compact, append a copy of
  // whatever the active log accumulated meanwhile (chasing it unlocked),
  // then take mu_ only for the final chase of the last few bytes + the
  // rename swap.  Writers stall only for that tail (the reference's
  // pebble compacts in the background the same way).  Crash-safe at
  // every step: Load() replays <path>.frozen before <path>, and a
  // leftover .compact is discarded.
  void FreezeLocked() {
    std::fflush(log_);
    std::fclose(log_);
    log_ = nullptr;
    // a leftover frozen log (previous compaction FAILED) still holds
    // the only on-disk copy of the pre-freeze records: fold it back
    // into one log first — never delete it
    FILE* probe = std::fopen(FrozenPath().c_str(), "rb");
    if (probe) {
      std::fclose(probe);
      CollapseFrozen();
    }
    std::rename(path_.c_str(), FrozenPath().c_str());
    log_ = std::fopen(path_.c_str(), "ab");
  }

  bool CompactFrozen() {
    std::string tmp = path_ + ".compact";
    bool ok = false;
    {
      std::map<std::string, std::string> frozen;
      ReplayFile(FrozenPath(), &frozen, nullptr);
      FILE* f = std::fopen(tmp.c_str(), "wb");
      if (!f) {
        compacting_ = false;
        return false;
      }
      std::string buf;
      ok = true;
      for (const auto& kv : frozen) {
        buf.clear();
        EncodeRecord(Record{1, kv.first, kv.second}, buf);
        if (std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
          ok = false;
          break;
        }
      }
      if (ok) {
        // chase the active log without stalling writers: anything
        // appended after the copy loop is picked up next pass
        long copied = 0;
        for (int pass = 0; ok && pass < 8; pass++) {
          long end = ActiveEndFlushed();
          if (end <= copied) break;
          ok = AppendRange(f, copied, end);
          copied = end;
          if (static_cast<long>(ActiveEndFlushed()) - copied < (1 << 20)) break;
        }
        if (ok) {
          // final tail + swap under the writer lock: bounded by what
          // arrived during the last unlocked pass
          std::lock_guard<std::mutex> g(mu_);
          std::fflush(log_);
          long end = FileEnd(path_);
          ok = AppendRange(f, copied, end);
          std::fflush(f);
          std::fclose(f);
          f = nullptr;
          if (ok) {
            std::fclose(log_);
            if (std::rename(tmp.c_str(), path_.c_str()) == 0) {
              std::remove(FrozenPath().c_str());
              dead_ = 0;
              writes_since_compact_ = 0;
            } else {
              ok = false;
            }
            log_ = std::fopen(path_.c_str(), "ab");
          }
        }
        if (f) std::fclose(f);
      } else {
        std::fclose(f);
      }
      if (!ok) std::remove(tmp.c_str());
    }
    if (!ok) {
      // back off: without this a failing compaction (e.g. disk full)
      // would re-trigger a full fold+rewrite on the next batch.  The
      // frozen log stays on disk and FreezeLocked folds it back in
      // before the retry, so no data is at risk.
      std::lock_guard<std::mutex> g(mu_);
      writes_since_compact_ = 0;
    }
    compacting_ = false;
    return ok;
  }

  // 1 = compacted, 0 = failed.  An explicit compaction is a promise of
  // reclaimed space: wait out any in-flight background run, then do a
  // full pass.  Writers still only stall for the tail copy + rename.
  int CompactNow() {
    while (compacting_.exchange(true)) {
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
    }
    {
      std::lock_guard<std::mutex> g(mu_);
      FreezeLocked();
    }
    if (compactor_.joinable()) compactor_.join();
    return CompactFrozen() ? 1 : 0;
  }

 private:
  static void EncodeRecord(const Record& r, std::string& out) {
    // [crc32 of payload][payload len][payload: type|klen|key|value]
    std::string payload;
    payload.push_back(static_cast<char>(r.type));
    put_u32(payload, static_cast<uint32_t>(r.key.size()));
    payload += r.key;
    payload += r.value;
    put_u32(out, crc32c(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size()));
    put_u32(out, static_cast<uint32_t>(payload.size()));
    out += payload;
  }

  std::string FrozenPath() const { return path_ + ".frozen"; }

  static long FileEnd(const std::string& p) {
    FILE* f = std::fopen(p.c_str(), "rb");
    if (!f) return 0;
    std::fseek(f, 0, SEEK_END);
    long end = std::ftell(f);
    std::fclose(f);
    return end;
  }

  long ActiveEndFlushed() {
    std::lock_guard<std::mutex> g(mu_);
    std::fflush(log_);
    return FileEnd(path_);
  }

  // copy bytes [from, to) of the active log into f (append-only source,
  // so an unlocked copy of an already-flushed range is stable)
  bool AppendRange(FILE* f, long from, long to) {
    if (to <= from) return true;
    FILE* src = std::fopen(path_.c_str(), "rb");
    if (!src) return false;
    std::fseek(src, from, SEEK_SET);
    std::vector<char> buf(1 << 20);
    long left = to - from;
    bool ok = true;
    while (left > 0) {
      size_t want = static_cast<size_t>(
          std::min(left, static_cast<long>(buf.size())));
      size_t n = std::fread(buf.data(), 1, want, src);
      if (n == 0) {
        ok = false;
        break;
      }
      if (std::fwrite(buf.data(), 1, n, f) != n) {
        ok = false;
        break;
      }
      left -= static_cast<long>(n);
    }
    std::fclose(src);
    return ok;
  }

  // replay a log file into `into`; reports the end of the last good
  // record via good_end when non-null (torn-tail truncation point)
  static void ReplayFile(const std::string& p,
                         std::map<std::string, std::string>* into,
                         long* good_end_out) {
    FILE* f = std::fopen(p.c_str(), "rb");
    if (!f) return;
    std::vector<uint8_t> hdr(8);
    std::vector<uint8_t> payload;
    long good_end = 0;
    while (true) {
      if (std::fread(hdr.data(), 1, 8, f) != 8) break;
      uint32_t crc = get_u32(hdr.data());
      uint32_t len = get_u32(hdr.data() + 4);
      if (len > (1u << 30)) break;  // corrupt length
      payload.resize(len);
      if (std::fread(payload.data(), 1, len, f) != len) break;
      if (crc32c(payload.data(), len) != crc) break;  // torn tail: stop
      uint8_t type = payload[0];
      uint32_t klen = get_u32(payload.data() + 1);
      if (5 + klen > len) break;
      std::string key(reinterpret_cast<char*>(payload.data() + 5), klen);
      if (type == 1) {
        (*into)[key] = std::string(
            reinterpret_cast<char*>(payload.data() + 5 + klen), len - 5 - klen);
      } else {
        into->erase(key);
      }
      good_end = std::ftell(f);
    }
    std::fclose(f);
    if (good_end_out) *good_end_out = good_end;
  }

  // A leftover frozen log (crash mid-compaction, or a failed run) must
  // be folded back into ONE on-disk log before any new freeze could
  // clobber it: truncate the frozen file at its last GOOD record (a
  // crash during a previous fold can leave a torn tail mid-file —
  // appending after it would make Load()'s torn-tail truncation eat
  // valid data later), append the active log, and make the result the
  // active log.  Replay order is preserved exactly.  Callers must have
  // log_ closed (constructor: not yet opened; FreezeLocked: just closed).
  void CollapseFrozen() {
    FILE* probe = std::fopen(FrozenPath().c_str(), "rb");
    if (!probe) return;
    std::fclose(probe);
    {
      std::map<std::string, std::string> scratch;
      long good = 0;
      ReplayFile(FrozenPath(), &scratch, &good);
      FILE* t = std::fopen(FrozenPath().c_str(), "rb+");
      if (t) {
        std::fseek(t, 0, SEEK_END);
        if (std::ftell(t) != good) (void)!ftruncate(fileno(t), good);
        std::fclose(t);
      }
    }
    FILE* f = std::fopen(FrozenPath().c_str(), "ab");
    if (!f) return;
    long end = FileEnd(path_);
    bool ok = AppendRange(f, 0, end);
    std::fflush(f);
    std::fclose(f);
    if (ok) {
      std::rename(FrozenPath().c_str(), path_.c_str());
    }
  }

  void Load() {
    // a crash mid-compaction leaves <path>.frozen (+ possibly .compact):
    // the frozen log holds everything before the freeze and replays
    // FIRST; a partial .compact is garbage
    std::remove((path_ + ".compact").c_str());
    ReplayFile(FrozenPath(), &index_, nullptr);
    long good_end = 0;
    ReplayFile(path_, &index_, &good_end);
    // truncate any torn tail so the append log stays well-formed
    if (good_end >= 0) {
      FILE* t = std::fopen(path_.c_str(), "rb+");
      if (t) {
        std::fseek(t, 0, SEEK_END);
        if (std::ftell(t) != good_end) {
          (void)!ftruncate(fileno(t), good_end);
        }
        std::fclose(t);
      }
    }
  }

  std::string path_;
  FILE* log_ = nullptr;
  std::map<std::string, std::string> index_;
  std::mutex mu_;
  size_t dead_ = 0;
  size_t writes_since_compact_ = 0;
  std::atomic<bool> compacting_{false};
  std::thread compactor_;
};

struct Iter {
  std::vector<std::pair<std::string, std::string>> items;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  auto* s = new KVStore(path);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) { delete static_cast<KVStore*>(h); }

// returns value length, -1 when missing; caller frees with kv_free
int64_t kv_get(void* h, const uint8_t* key, uint32_t klen, uint8_t** out) {
  std::string* v = nullptr;
  static_cast<KVStore*>(h)->Get(std::string((const char*)key, klen), &v);
  if (!v) return -1;
  int64_t n = static_cast<int64_t>(v->size());
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(v->size()));
  std::memcpy(buf, v->data(), v->size());
  *out = buf;
  delete v;
  return n;
}

void kv_free(uint8_t* p) { std::free(p); }

int kv_has(void* h, const uint8_t* key, uint32_t klen) {
  return static_cast<KVStore*>(h)->Has(std::string((const char*)key, klen));
}

// batch format from python: repeated [type u8][klen u32][vlen u32][key][value]
int kv_write_batch(void* h, const uint8_t* data, uint64_t len) {
  std::vector<Record> recs;
  uint64_t off = 0;
  while (off + 9 <= len) {
    Record r;
    r.type = data[off];
    uint32_t klen = get_u32(data + off + 1);
    uint32_t vlen = get_u32(data + off + 5);
    off += 9;
    if (off + klen + vlen > len) return 0;
    r.key.assign((const char*)data + off, klen);
    off += klen;
    r.value.assign((const char*)data + off, vlen);
    off += vlen;
    recs.push_back(std::move(r));
  }
  if (off != len) return 0;
  return static_cast<KVStore*>(h)->WriteBatch(recs) ? 1 : 0;
}

void* kv_range(void* h, const uint8_t* start, uint32_t slen, const uint8_t* end,
               uint32_t elen, int reverse) {
  auto* it = new Iter();
  static_cast<KVStore*>(h)->Range(std::string((const char*)start, slen),
                                  std::string((const char*)end, elen),
                                  reverse != 0, &it->items);
  return it;
}

// 1 if a pair was produced; buffers freed with kv_free
int kv_iter_next(void* ih, uint8_t** key, uint64_t* klen, uint8_t** val,
                 uint64_t* vlen) {
  auto* it = static_cast<Iter*>(ih);
  if (it->pos >= it->items.size()) return 0;
  const auto& kv = it->items[it->pos++];
  *klen = kv.first.size();
  *vlen = kv.second.size();
  uint8_t* kb = static_cast<uint8_t*>(std::malloc(kv.first.size()));
  std::memcpy(kb, kv.first.data(), kv.first.size());
  uint8_t* vb = static_cast<uint8_t*>(std::malloc(kv.second.size()));
  std::memcpy(vb, kv.second.data(), kv.second.size());
  *key = kb;
  *val = vb;
  return 1;
}

void kv_iter_close(void* ih) { delete static_cast<Iter*>(ih); }

uint64_t kv_size(void* h) { return static_cast<KVStore*>(h)->Size(); }

int kv_compact(void* h) { return static_cast<KVStore*>(h)->CompactNow(); }

}  // extern "C"
