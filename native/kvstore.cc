// Native storage engine for the block/state stores.
//
// Reference parity target: the role pebble plays in the reference
// (db/pebbledb.go — an ordered, batched, persistent KV store).  Design
// here is a single-writer log-structured store: an append-only value log
// with CRC-framed records, an in-memory ordered index (std::map) rebuilt
// from the log on open, and periodic compaction that rewrites the live
// set.  That matches this engine's actual workload — blocks and state
// snapshots are written once per height in one batch, read by key or by
// short ordered range scans, and pruned from the tail — without dragging
// in a full LSM tree.
//
// Exposed as a C ABI for ctypes (no pybind11 in the image).

#include <unistd.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

namespace {

uint32_t crc32c(const uint8_t* data, size_t n) {
  // CRC-32 (Castagnoli polynomial, bitwise; cold path only)
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) {
    crc ^= data[i];
    for (int k = 0; k < 8; k++)
      crc = (crc >> 1) ^ (0x82F63B78u & (0u - (crc & 1u)));
  }
  return crc ^ 0xFFFFFFFFu;
}

struct Record {
  uint8_t type;  // 1 = set, 2 = delete
  std::string key;
  std::string value;
};

void put_u32(std::string& out, uint32_t v) {
  out.push_back(static_cast<char>(v & 0xff));
  out.push_back(static_cast<char>((v >> 8) & 0xff));
  out.push_back(static_cast<char>((v >> 16) & 0xff));
  out.push_back(static_cast<char>((v >> 24) & 0xff));
}

uint32_t get_u32(const uint8_t* p) {
  return (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
         ((uint32_t)p[3] << 24);
}

class KVStore {
 public:
  explicit KVStore(const std::string& path) : path_(path) {
    Load();
    log_ = std::fopen(path_.c_str(), "ab");
  }

  ~KVStore() {
    if (log_) std::fclose(log_);
  }

  bool ok() const { return log_ != nullptr; }

  void Get(const std::string& key, std::string** out) {
    std::lock_guard<std::mutex> g(mu_);
    auto it = index_.find(key);
    *out = (it == index_.end()) ? nullptr : new std::string(it->second);
  }

  bool Has(const std::string& key) {
    std::lock_guard<std::mutex> g(mu_);
    return index_.count(key) != 0;
  }

  // one durable batch (fsync'd): the per-height write unit
  bool WriteBatch(const std::vector<Record>& recs) {
    std::lock_guard<std::mutex> g(mu_);
    std::string buf;
    for (const auto& r : recs) EncodeRecord(r, buf);
    if (std::fwrite(buf.data(), 1, buf.size(), log_) != buf.size()) return false;
    if (std::fflush(log_) != 0) return false;
    for (const auto& r : recs) {
      if (r.type == 1)
        index_[r.key] = r.value;
      else
        index_.erase(r.key);
      dead_ += (r.type == 2) ? 1 : 0;
    }
    writes_since_compact_ += recs.size();
    if (writes_since_compact_ > 200000 && dead_ * 4 > index_.size()) Compact();
    return true;
  }

  // ordered iteration [start, end) — collected under the lock so the
  // caller gets a stable snapshot
  void Range(const std::string& start, const std::string& end, bool reverse,
             std::vector<std::pair<std::string, std::string>>* out) {
    std::lock_guard<std::mutex> g(mu_);
    auto lo = start.empty() ? index_.begin() : index_.lower_bound(start);
    auto hi = end.empty() ? index_.end() : index_.lower_bound(end);
    for (auto it = lo; it != hi; ++it) out->push_back(*it);
    if (reverse) std::reverse(out->begin(), out->end());
  }

  size_t Size() {
    std::lock_guard<std::mutex> g(mu_);
    return index_.size();
  }

  bool Compact() {
    // rewrite only the live set; callers hold mu_
    std::string tmp = path_ + ".compact";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) return false;
    std::string buf;
    for (const auto& kv : index_) {
      buf.clear();
      EncodeRecord(Record{1, kv.first, kv.second}, buf);
      if (std::fwrite(buf.data(), 1, buf.size(), f) != buf.size()) {
        std::fclose(f);
        std::remove(tmp.c_str());
        return false;
      }
    }
    std::fflush(f);
    std::fclose(f);
    std::fclose(log_);
    if (std::rename(tmp.c_str(), path_.c_str()) != 0) {
      log_ = std::fopen(path_.c_str(), "ab");
      return false;
    }
    log_ = std::fopen(path_.c_str(), "ab");
    dead_ = 0;
    writes_since_compact_ = 0;
    return true;
  }

  bool CompactNow() {
    std::lock_guard<std::mutex> g(mu_);
    return Compact();
  }

 private:
  static void EncodeRecord(const Record& r, std::string& out) {
    // [crc32 of payload][payload len][payload: type|klen|key|value]
    std::string payload;
    payload.push_back(static_cast<char>(r.type));
    put_u32(payload, static_cast<uint32_t>(r.key.size()));
    payload += r.key;
    payload += r.value;
    put_u32(out, crc32c(reinterpret_cast<const uint8_t*>(payload.data()),
                        payload.size()));
    put_u32(out, static_cast<uint32_t>(payload.size()));
    out += payload;
  }

  void Load() {
    FILE* f = std::fopen(path_.c_str(), "rb");
    if (!f) return;
    std::vector<uint8_t> hdr(8);
    std::vector<uint8_t> payload;
    long good_end = 0;
    while (true) {
      if (std::fread(hdr.data(), 1, 8, f) != 8) break;
      uint32_t crc = get_u32(hdr.data());
      uint32_t len = get_u32(hdr.data() + 4);
      if (len > (1u << 30)) break;  // corrupt length
      payload.resize(len);
      if (std::fread(payload.data(), 1, len, f) != len) break;
      if (crc32c(payload.data(), len) != crc) break;  // torn tail: stop
      uint8_t type = payload[0];
      uint32_t klen = get_u32(payload.data() + 1);
      if (5 + klen > len) break;
      std::string key(reinterpret_cast<char*>(payload.data() + 5), klen);
      if (type == 1) {
        index_[key] = std::string(
            reinterpret_cast<char*>(payload.data() + 5 + klen), len - 5 - klen);
      } else {
        index_.erase(key);
      }
      good_end = std::ftell(f);
    }
    std::fclose(f);
    // truncate any torn tail so the append log stays well-formed
    if (good_end >= 0) {
      FILE* t = std::fopen(path_.c_str(), "rb+");
      if (t) {
#ifdef _WIN32
#else
        if (std::ftell(t) != good_end) {
          // use ftruncate via fileno
          (void)!ftruncate(fileno(t), good_end);
        }
#endif
        std::fclose(t);
      }
    }
  }

  std::string path_;
  FILE* log_ = nullptr;
  std::map<std::string, std::string> index_;
  std::mutex mu_;
  size_t dead_ = 0;
  size_t writes_since_compact_ = 0;
};

struct Iter {
  std::vector<std::pair<std::string, std::string>> items;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* kv_open(const char* path) {
  auto* s = new KVStore(path);
  if (!s->ok()) {
    delete s;
    return nullptr;
  }
  return s;
}

void kv_close(void* h) { delete static_cast<KVStore*>(h); }

// returns value length, -1 when missing; caller frees with kv_free
int64_t kv_get(void* h, const uint8_t* key, uint32_t klen, uint8_t** out) {
  std::string* v = nullptr;
  static_cast<KVStore*>(h)->Get(std::string((const char*)key, klen), &v);
  if (!v) return -1;
  int64_t n = static_cast<int64_t>(v->size());
  uint8_t* buf = static_cast<uint8_t*>(std::malloc(v->size()));
  std::memcpy(buf, v->data(), v->size());
  *out = buf;
  delete v;
  return n;
}

void kv_free(uint8_t* p) { std::free(p); }

int kv_has(void* h, const uint8_t* key, uint32_t klen) {
  return static_cast<KVStore*>(h)->Has(std::string((const char*)key, klen));
}

// batch format from python: repeated [type u8][klen u32][vlen u32][key][value]
int kv_write_batch(void* h, const uint8_t* data, uint64_t len) {
  std::vector<Record> recs;
  uint64_t off = 0;
  while (off + 9 <= len) {
    Record r;
    r.type = data[off];
    uint32_t klen = get_u32(data + off + 1);
    uint32_t vlen = get_u32(data + off + 5);
    off += 9;
    if (off + klen + vlen > len) return 0;
    r.key.assign((const char*)data + off, klen);
    off += klen;
    r.value.assign((const char*)data + off, vlen);
    off += vlen;
    recs.push_back(std::move(r));
  }
  if (off != len) return 0;
  return static_cast<KVStore*>(h)->WriteBatch(recs) ? 1 : 0;
}

void* kv_range(void* h, const uint8_t* start, uint32_t slen, const uint8_t* end,
               uint32_t elen, int reverse) {
  auto* it = new Iter();
  static_cast<KVStore*>(h)->Range(std::string((const char*)start, slen),
                                  std::string((const char*)end, elen),
                                  reverse != 0, &it->items);
  return it;
}

// 1 if a pair was produced; buffers freed with kv_free
int kv_iter_next(void* ih, uint8_t** key, uint64_t* klen, uint8_t** val,
                 uint64_t* vlen) {
  auto* it = static_cast<Iter*>(ih);
  if (it->pos >= it->items.size()) return 0;
  const auto& kv = it->items[it->pos++];
  *klen = kv.first.size();
  *vlen = kv.second.size();
  uint8_t* kb = static_cast<uint8_t*>(std::malloc(kv.first.size()));
  std::memcpy(kb, kv.first.data(), kv.first.size());
  uint8_t* vb = static_cast<uint8_t*>(std::malloc(kv.second.size()));
  std::memcpy(vb, kv.second.data(), kv.second.size());
  *key = kb;
  *val = vb;
  return 1;
}

void kv_iter_close(void* ih) { delete static_cast<Iter*>(ih); }

uint64_t kv_size(void* h) { return static_cast<KVStore*>(h)->Size(); }

int kv_compact(void* h) { return static_cast<KVStore*>(h)->CompactNow() ? 1 : 0; }

}  // extern "C"
