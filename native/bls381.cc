// BLS12-381 pairing core for the cometbft_tpu bls12_381 key type.
//
// The engine's pure-Python pairing (cometbft_tpu/crypto/bls12381.py) is
// ~1 s per pairing — unusable for the 10k-validator aggregate config.
// This is the native equivalent of the reference's blst dependency
// (crypto/bls12381/key_bls12381.go:40-41,179): an original, compact
// implementation of the optimal-ate pairing product check
//     prod_i e(P_i, Q_i) == 1,   P_i in G1, Q_i in G2,
// which is the only primitive signature verification needs
// (verify = e(-g1, sig) * e(pk, H(m)) == 1; aggregates likewise).
//
// Design notes:
//  - Fp: 6x64-bit Montgomery (CIOS with __uint128).  Constants (R^2,
//    n0') are derived at load time from the modulus, not embedded.
//  - Towers: Fp2 = Fp[u]/(u^2+1); Fp12 = Fp2[w]/(w^6 - xi), xi = 1+u —
//    the same direct degree-6 representation the Python module uses, so
//    the two implementations can be diffed coefficient-by-coefficient.
//  - Miller loop: Jacobian doubling/addition on the TWISTED curve (all
//    point arithmetic in Fp2) with sparse line evaluations placed at
//    w^0 / w^3 / w^5.  The placement follows from the module's untwist
//    convention (bls12381.py _untwist: x = x' w^-2, y = y' w^-3):
//        L = yp - lam' xp w^-1 + (lam' x1' - y1') w^-3,
//    rewritten with w^-1 = w^5 xi^-1, w^-3 = w^3 xi^-1 and scaled by
//    the Fp2 denominator (subfield factors are killed by the final
//    exponentiation, so lines may be scaled by any Fp/Fp2 constant).
//  - Final exponentiation: easy part ((p^6-1)(p^2+1)) with one tower
//    inversion, hard part (p^4-p^2+1)/r by plain square-and-multiply
//    (the exponent bytes are derived at load time from p and r).
//
// Exceptional cases (T == +-Q mid-loop) cannot occur for inputs in the
// prime-order subgroups, which callers enforce (bls12381.py checks
// subgroup membership on deserialization).

#include <cstdint>
#include <cstring>

typedef unsigned __int128 u128;
typedef uint64_t u64;

static const int NL = 6;  // 64-bit limbs per Fp element

// p, little-endian limbs
static const u64 Pmod[NL] = {
    0xb9feffffffffaaabULL, 0x1eabfffeb153ffffULL, 0x6730d2a0f6b0f624ULL,
    0x64774b84f38512bfULL, 0x4b1ba7b6434bacd7ULL, 0x1a0111ea397fe69aULL,
};
// r (group order), little-endian limbs (255 bits -> 4 limbs)
static const u64 Rord[4] = {
    0xffffffff00000001ULL, 0x53bda402fffe5bfeULL,
    0x3339d80809a1d805ULL, 0x73eda753299d7d48ULL,
};
// |x| for BLS12-381 (x = -0xd201000000010000)
static const u64 X_ABS = 0xd201000000010000ULL;

static u64 N0INV;       // -p^-1 mod 2^64
static u64 R2[NL];      // 2^768 mod p (to-Montgomery factor)
static u64 ONE_M[NL];   // 1 in Montgomery form (= 2^384 mod p)

// ---------------------------------------------------------------- raw ops

static inline int raw_add(u64* o, const u64* a, const u64* b) {
  u128 c = 0;
  for (int i = 0; i < NL; i++) {
    c += (u128)a[i] + b[i];
    o[i] = (u64)c;
    c >>= 64;
  }
  return (int)c;
}

static inline int raw_sub(u64* o, const u64* a, const u64* b) {
  u128 br = 0;
  for (int i = 0; i < NL; i++) {
    u128 d = (u128)a[i] - b[i] - br;
    o[i] = (u64)d;
    br = (d >> 64) & 1;
  }
  return (int)br;
}

static inline int raw_cmp(const u64* a, const u64* b) {
  for (int i = NL - 1; i >= 0; i--) {
    if (a[i] != b[i]) return a[i] < b[i] ? -1 : 1;
  }
  return 0;
}

// ------------------------------------------------------------- Fp (Mont)

struct Fp {
  u64 v[NL];
};

static inline void fp_zero(Fp& o) { memset(o.v, 0, sizeof o.v); }
static inline bool fp_is_zero(const Fp& a) {
  u64 x = 0;
  for (int i = 0; i < NL; i++) x |= a.v[i];
  return x == 0;
}
static inline bool fp_eq(const Fp& a, const Fp& b) {
  return memcmp(a.v, b.v, sizeof a.v) == 0;
}

static inline void fp_add(Fp& o, const Fp& a, const Fp& b) {
  int carry = raw_add(o.v, a.v, b.v);
  if (carry || raw_cmp(o.v, Pmod) >= 0) raw_sub(o.v, o.v, Pmod);
}

static inline void fp_sub(Fp& o, const Fp& a, const Fp& b) {
  if (raw_sub(o.v, a.v, b.v)) raw_add(o.v, o.v, Pmod);
}

static inline void fp_neg(Fp& o, const Fp& a) {
  if (fp_is_zero(a)) { o = a; return; }
  raw_sub(o.v, Pmod, a.v);
}

// CIOS Montgomery multiplication: o = a*b*2^-384 mod p
static void fp_mul(Fp& o, const Fp& a, const Fp& b) {
  u64 t[NL + 2] = {0};
  for (int i = 0; i < NL; i++) {
    u128 c = 0;
    for (int j = 0; j < NL; j++) {
      c += (u128)t[j] + (u128)a.v[i] * b.v[j];
      t[j] = (u64)c;
      c >>= 64;
    }
    c += t[NL];
    t[NL] = (u64)c;
    t[NL + 1] = (u64)(c >> 64);
    u64 m = t[0] * N0INV;
    c = (u128)t[0] + (u128)m * Pmod[0];
    c >>= 64;
    for (int j = 1; j < NL; j++) {
      c += (u128)t[j] + (u128)m * Pmod[j];
      t[j - 1] = (u64)c;
      c >>= 64;
    }
    c += t[NL];
    t[NL - 1] = (u64)c;
    t[NL] = t[NL + 1] + (u64)(c >> 64);
  }
  memcpy(o.v, t, sizeof o.v);
  if (t[NL] || raw_cmp(o.v, Pmod) >= 0) raw_sub(o.v, o.v, Pmod);
}

static inline void fp_sqr(Fp& o, const Fp& a) { fp_mul(o, a, a); }

static Fp ONE_M_fp();

static void fp_pow_pm2(Fp& o, const Fp& a) {
  // a^(p-2): Fermat inversion.  MSB-first square-and-multiply over p-2.
  u64 e[NL];
  u64 two[NL] = {2, 0, 0, 0, 0, 0};
  raw_sub(e, Pmod, two);
  Fp r = ONE_M_fp();
  for (int i = NL * 64 - 1; i >= 0; i--) {
    fp_sqr(r, r);
    if ((e[i / 64] >> (i % 64)) & 1) fp_mul(r, r, a);
  }
  o = r;
}

static Fp ONE_M_fp() {
  Fp x;
  memcpy(x.v, ONE_M, sizeof x.v);
  return x;
}

// ------------------------------------------------------------------- Fp2

struct Fp2 {
  Fp c0, c1;
};

static inline void f2_add(Fp2& o, const Fp2& a, const Fp2& b) {
  fp_add(o.c0, a.c0, b.c0);
  fp_add(o.c1, a.c1, b.c1);
}
static inline void f2_sub(Fp2& o, const Fp2& a, const Fp2& b) {
  fp_sub(o.c0, a.c0, b.c0);
  fp_sub(o.c1, a.c1, b.c1);
}
static inline void f2_neg(Fp2& o, const Fp2& a) {
  fp_neg(o.c0, a.c0);
  fp_neg(o.c1, a.c1);
}
static inline bool f2_is_zero(const Fp2& a) {
  return fp_is_zero(a.c0) && fp_is_zero(a.c1);
}
static inline bool f2_eq(const Fp2& a, const Fp2& b) {
  return fp_eq(a.c0, b.c0) && fp_eq(a.c1, b.c1);
}

static void f2_mul(Fp2& o, const Fp2& a, const Fp2& b) {
  // (a0 + a1 u)(b0 + b1 u), u^2 = -1 (3-mul Karatsuba)
  Fp t0, t1, t2, t3;
  fp_mul(t0, a.c0, b.c0);
  fp_mul(t1, a.c1, b.c1);
  fp_add(t2, a.c0, a.c1);
  fp_add(t3, b.c0, b.c1);
  fp_mul(t2, t2, t3);
  fp_sub(t2, t2, t0);
  fp_sub(t2, t2, t1);
  fp_sub(o.c0, t0, t1);
  o.c1 = t2;
}

static void f2_sqr(Fp2& o, const Fp2& a) {
  // (a0+a1)(a0-a1), 2 a0 a1
  Fp s, d, m;
  fp_add(s, a.c0, a.c1);
  fp_sub(d, a.c0, a.c1);
  fp_mul(m, a.c0, a.c1);
  fp_mul(o.c0, s, d);
  fp_add(o.c1, m, m);
}

static void f2_mul_fp(Fp2& o, const Fp2& a, const Fp& k) {
  fp_mul(o.c0, a.c0, k);
  fp_mul(o.c1, a.c1, k);
}

static void f2_mul_xi(Fp2& o, const Fp2& a) {
  // xi = 1 + u: (a0 - a1) + (a0 + a1) u
  Fp t0, t1;
  fp_sub(t0, a.c0, a.c1);
  fp_add(t1, a.c0, a.c1);
  o.c0 = t0;
  o.c1 = t1;
}

static void f2_inv(Fp2& o, const Fp2& a) {
  // 1/(a0 + a1 u) = (a0 - a1 u)/(a0^2 + a1^2)
  Fp n, t;
  fp_sqr(n, a.c0);
  fp_sqr(t, a.c1);
  fp_add(n, n, t);
  fp_pow_pm2(n, n);
  fp_mul(o.c0, a.c0, n);
  fp_neg(t, a.c1);
  fp_mul(o.c1, t, n);
}

// ------------------------------------------------------------------ Fp12
// Direct degree-6 extension over Fp2: sum c[i] w^i, w^6 = xi.

struct Fp12 {
  Fp2 c[6];
};

static void f12_one(Fp12& o) {
  memset(&o, 0, sizeof o);
  o.c[0].c0 = ONE_M_fp();
}

static bool f12_is_one(const Fp12& a) {
  Fp12 one;
  f12_one(one);
  for (int i = 0; i < 6; i++)
    if (!f2_eq(a.c[i], one.c[i])) return false;
  return true;
}

static void f12_mul(Fp12& o, const Fp12& x, const Fp12& y) {
  Fp2 acc[11];
  memset(acc, 0, sizeof acc);
  Fp2 t;
  for (int i = 0; i < 6; i++) {
    if (f2_is_zero(x.c[i])) continue;
    for (int j = 0; j < 6; j++) {
      if (f2_is_zero(y.c[j])) continue;
      f2_mul(t, x.c[i], y.c[j]);
      f2_add(acc[i + j], acc[i + j], t);
    }
  }
  for (int k = 10; k >= 6; k--) {
    f2_mul_xi(t, acc[k]);
    f2_add(acc[k - 6], acc[k - 6], t);
  }
  memcpy(o.c, acc, sizeof o.c);
}

static void f12_sqr(Fp12& o, const Fp12& a) { f12_mul(o, a, a); }

static void f12_conj(Fp12& o, const Fp12& a) {
  // w -> -w: negate odd coefficients (the p^6 Frobenius)
  o = a;
  f2_neg(o.c[1], a.c[1]);
  f2_neg(o.c[3], a.c[3]);
  f2_neg(o.c[5], a.c[5]);
}

// Frobenius x -> x^p: conj each Fp2 coefficient, multiply c[i] by
// xi^(i(p-1)/6).  The constants are computed at load time.
static Fp2 FROB_C[6];

static void f2_conj(Fp2& o, const Fp2& a) {
  o.c0 = a.c0;
  fp_neg(o.c1, a.c1);
}

static void f12_frob(Fp12& o, const Fp12& a) {
  Fp2 t;
  for (int i = 0; i < 6; i++) {
    f2_conj(t, a.c[i]);
    f2_mul(o.c[i], t, FROB_C[i]);
  }
}

// Tower inversion: write a = A(w^2) + w B(w^2) with A,B in Fp6 =
// Fp2[v]/(v^3 - xi), v = w^2.  Then 1/a = (A - wB) / (A^2 - v B^2 ...)
// — rather than juggling the iso, invert via the adjugate over Fp6.
struct Fp6 {
  Fp2 c[3];  // over v, v^3 = xi
};

static void f6_mul(Fp6& o, const Fp6& a, const Fp6& b) {
  Fp2 acc[5];
  memset(acc, 0, sizeof acc);
  Fp2 t;
  for (int i = 0; i < 3; i++)
    for (int j = 0; j < 3; j++) {
      f2_mul(t, a.c[i], b.c[j]);
      f2_add(acc[i + j], acc[i + j], t);
    }
  for (int k = 4; k >= 3; k--) {
    f2_mul_xi(t, acc[k]);
    f2_add(acc[k - 3], acc[k - 3], t);
  }
  memcpy(o.c, acc, sizeof o.c);
}

static void f6_sub(Fp6& o, const Fp6& a, const Fp6& b) {
  for (int i = 0; i < 3; i++) f2_sub(o.c[i], a.c[i], b.c[i]);
}

static void f6_mul_v(Fp6& o, const Fp6& a) {
  // multiply by v: (c2 xi, c0, c1)
  Fp2 t;
  f2_mul_xi(t, a.c[2]);
  Fp2 c0 = a.c[0], c1 = a.c[1];
  o.c[0] = t;
  o.c[1] = c0;
  o.c[2] = c1;
}

static void f6_inv(Fp6& o, const Fp6& a) {
  // adjugate method: standard for cubic extensions (v^3 = xi)
  Fp2 A, B, C, t0, t1;
  // A = c0^2 - xi c1 c2 ; B = xi c2^2 - c0 c1 ; C = c1^2 - c0 c2
  f2_sqr(A, a.c[0]);
  f2_mul(t0, a.c[1], a.c[2]);
  f2_mul_xi(t0, t0);
  f2_sub(A, A, t0);
  f2_sqr(B, a.c[2]);
  f2_mul_xi(B, B);
  f2_mul(t0, a.c[0], a.c[1]);
  f2_sub(B, B, t0);
  f2_sqr(C, a.c[1]);
  f2_mul(t0, a.c[0], a.c[2]);
  f2_sub(C, C, t0);
  // F = c0 A + xi (c1 C + c2 B)
  Fp2 F;
  f2_mul(t0, a.c[1], C);
  f2_mul(t1, a.c[2], B);
  f2_add(t0, t0, t1);
  f2_mul_xi(t0, t0);
  f2_mul(F, a.c[0], A);
  f2_add(F, F, t0);
  f2_inv(F, F);
  f2_mul(o.c[0], A, F);
  f2_mul(o.c[1], B, F);
  f2_mul(o.c[2], C, F);
}

static void f12_to_tower(const Fp12& a, Fp6& A, Fp6& B) {
  // a = A(v) + w B(v), v = w^2: even coeffs -> A, odd -> B
  A.c[0] = a.c[0];
  A.c[1] = a.c[2];
  A.c[2] = a.c[4];
  B.c[0] = a.c[1];
  B.c[1] = a.c[3];
  B.c[2] = a.c[5];
}

static void f12_from_tower(Fp12& o, const Fp6& A, const Fp6& B) {
  o.c[0] = A.c[0];
  o.c[2] = A.c[1];
  o.c[4] = A.c[2];
  o.c[1] = B.c[0];
  o.c[3] = B.c[1];
  o.c[5] = B.c[2];
}

static void f12_inv(Fp12& o, const Fp12& a) {
  // 1/(A + wB) = (A - wB)/(A^2 - v B^2)   [w^2 = v]
  Fp6 A, B, A2, B2, D, Di, oA, oB;
  f12_to_tower(a, A, B);
  f6_mul(A2, A, A);
  f6_mul(B2, B, B);
  f6_mul_v(B2, B2);
  f6_sub(D, A2, B2);
  f6_inv(Di, D);
  f6_mul(oA, A, Di);
  Fp6 negDi;
  for (int i = 0; i < 3; i++) f2_neg(negDi.c[i], Di.c[i]);
  f6_mul(oB, B, negDi);
  f12_from_tower(o, oA, oB);
}

// ----------------------------------------------------------- curve types

struct G1Aff {
  Fp x, y;
};
struct G2Aff {
  Fp2 x, y;
};
struct G2Jac {
  Fp2 X, Y, Z;
};

// ------------------------------------------------------------ Miller loop

static Fp2 XI_INV;  // (1+u)^-1, for the w^-1/w^-3 rewrite

// Doubling step: T <- 2T; line through tangent at T, evaluated at P.
static void dbl_step(Fp12& f, G2Jac& T, const G1Aff& p) {
  Fp2 A, B, C, D, E, F, t;
  f2_sqr(A, T.X);                    // X^2
  f2_sqr(B, T.Y);                    // Y^2
  f2_sqr(C, B);                      // Y^4
  f2_add(D, T.X, B);
  f2_sqr(D, D);
  f2_sub(D, D, A);
  f2_sub(D, D, C);
  f2_add(D, D, D);                   // D = 2((X+B)^2 - A - C) = 4XY^2
  f2_add(E, A, A);
  f2_add(E, E, A);                   // E = 3X^2
  f2_sqr(F, E);

  // line (scaled by 2YZ^3, an Fp2 constant — vanishes in final exp):
  //   a0 = 2YZ^3 * yp
  //   a5 = -3X^2 Z^2 * xp * xi^-1
  //   a3 = (3X^3 - 2Y^2) * xi^-1
  Fp2 Z2, l3, l5;
  f2_sqr(Z2, T.Z);
  f2_mul(t, T.Y, T.Z);
  f2_mul(t, t, Z2);
  f2_add(t, t, t);                   // 2YZ^3
  // a0 = 2YZ^3 * yp is Fp2 in general (2YZ^3 is Fp2)
  Fp2 a0v;
  f2_mul_fp(a0v, t, p.y);
  f2_mul(l5, E, Z2);
  f2_mul_fp(l5, l5, p.x);
  f2_neg(l5, l5);
  f2_mul(l5, l5, XI_INV);
  Fp2 X3cu;
  f2_mul(X3cu, A, T.X);              // X^3
  f2_add(t, X3cu, X3cu);
  f2_add(t, t, X3cu);                // 3X^3
  Fp2 twoB;
  f2_add(twoB, B, B);                // 2Y^2
  f2_sub(l3, t, twoB);
  f2_mul(l3, l3, XI_INV);

  Fp12 l;
  memset(&l, 0, sizeof l);
  l.c[0] = a0v;
  l.c[3] = l3;
  l.c[5] = l5;
  f12_mul(f, f, l);

  // point update
  Fp2 X3, Y3, Z3;
  f2_sub(X3, F, D);
  f2_sub(X3, X3, D);                 // F - 2D
  f2_mul(Z3, T.Y, T.Z);
  f2_add(Z3, Z3, Z3);                // 2YZ
  f2_sub(t, D, X3);
  f2_mul(Y3, E, t);
  Fp2 eightC;
  f2_add(eightC, C, C);
  f2_add(eightC, eightC, eightC);
  f2_add(eightC, eightC, eightC);    // 8C
  f2_sub(Y3, Y3, eightC);
  T.X = X3;
  T.Y = Y3;
  T.Z = Z3;
}

// Mixed addition step: T <- T + Q; line through T and Q, evaluated at P.
static void add_step(Fp12& f, G2Jac& T, const G2Aff& q, const G1Aff& p) {
  Fp2 Z2, Z3, U2, S2, H, Rr, t;
  f2_sqr(Z2, T.Z);
  f2_mul(Z3, Z2, T.Z);
  f2_mul(U2, q.x, Z2);
  f2_mul(S2, q.y, Z3);
  f2_sub(H, U2, T.X);                // H = xq Z^2 - X
  f2_sub(Rr, S2, T.Y);               // r = yq Z^3 - Y

  // line (scaled by -(Z H), an Fp2 constant):
  //   a0 = ZH * yp ; a5 = -r * xp * xi^-1 ; a3 = (r xq - ZH yq) * xi^-1
  Fp2 ZH, a0v, l3, l5;
  f2_mul(ZH, T.Z, H);
  f2_mul_fp(a0v, ZH, p.y);
  f2_mul_fp(l5, Rr, p.x);
  f2_neg(l5, l5);
  f2_mul(l5, l5, XI_INV);
  f2_mul(l3, Rr, q.x);
  f2_mul(t, ZH, q.y);
  f2_sub(l3, l3, t);
  f2_mul(l3, l3, XI_INV);

  Fp12 l;
  memset(&l, 0, sizeof l);
  l.c[0] = a0v;
  l.c[3] = l3;
  l.c[5] = l5;
  f12_mul(f, f, l);

  // point update (Jacobian mixed addition)
  Fp2 H2, H3, U1H2, X3, Y3;
  f2_sqr(H2, H);
  f2_mul(H3, H2, H);
  f2_mul(U1H2, T.X, H2);
  f2_sqr(X3, Rr);
  f2_sub(X3, X3, H3);
  f2_sub(X3, X3, U1H2);
  f2_sub(X3, X3, U1H2);              // r^2 - H^3 - 2 X H^2
  f2_sub(t, U1H2, X3);
  f2_mul(Y3, Rr, t);
  f2_mul(t, T.Y, H3);
  f2_sub(Y3, Y3, t);                 // r(XH^2 - X3) - Y H^3
  Fp2 Z3n;
  f2_mul(Z3n, T.Z, H);
  T.X = X3;
  T.Y = Y3;
  T.Z = Z3n;
}

static void miller(Fp12& f, const G2Aff& q, const G1Aff& p) {
  G2Jac T;
  T.X = q.x;
  T.Y = q.y;
  memset(&T.Z, 0, sizeof T.Z);
  T.Z.c0 = ONE_M_fp();
  f12_one(f);
  int top = 63;
  while (!((X_ABS >> top) & 1)) top--;
  for (int i = top - 1; i >= 0; i--) {
    f12_sqr(f, f);
    dbl_step(f, T, p);
    if ((X_ABS >> i) & 1) add_step(f, T, q, p);
  }
}

// --------------------------------------------------- final exponentiation

// hard exponent (p^4 - p^2 + 1)/r, big-endian bits; computed at init
static u64 HARD[40];  // enough limbs for ~1270 bits
static int HARD_BITS;

static void f12_pow_hard(Fp12& o, const Fp12& g) {
  Fp12 r;
  f12_one(r);
  for (int i = HARD_BITS - 1; i >= 0; i--) {
    f12_sqr(r, r);
    if ((HARD[i / 64] >> (i % 64)) & 1) f12_mul(r, r, g);
  }
  o = r;
}

static void final_exp(Fp12& o, const Fp12& f) {
  Fp12 fc, fi, g, g2;
  f12_conj(fc, f);
  f12_inv(fi, f);
  f12_mul(g, fc, fi);        // f^(p^6 - 1)
  f12_frob(g2, g);
  f12_frob(g2, g2);
  f12_mul(g, g2, g);         // ^(p^2 + 1)
  f12_pow_hard(o, g);
}

// ------------------------------------------------- big-int init helpers

// HARD = (p^4 - p^2 + 1) / r, computed with schoolbook bignum at init.
// Working radix 2^32 to keep the division simple.
static void compute_hard() {
  // p as 12 32-bit digits
  const int W = 64;  // 32-bit digits, generous
  uint32_t p32[W] = {0}, acc[W] = {0}, p2[W] = {0}, p4[W] = {0};
  for (int i = 0; i < NL; i++) {
    p32[2 * i] = (uint32_t)Pmod[i];
    p32[2 * i + 1] = (uint32_t)(Pmod[i] >> 32);
  }
  auto mul = [&](const uint32_t* a, const uint32_t* b, uint32_t* o) {
    uint64_t tmp[2 * W] = {0};
    for (int i = 0; i < W; i++) {
      if (!a[i]) continue;
      uint64_t carry = 0;
      for (int j = 0; j + i < W; j++) {
        uint64_t cur = tmp[i + j] + (uint64_t)a[i] * b[j] + carry;
        tmp[i + j] = (uint32_t)cur;
        carry = cur >> 32;
      }
    }
    for (int i = 0; i < W; i++) o[i] = (uint32_t)tmp[i];
  };
  mul(p32, p32, p2);   // p^2
  mul(p2, p2, p4);     // p^4
  // acc = p^4 - p^2 + 1
  int64_t borrow = 0;
  for (int i = 0; i < W; i++) {
    int64_t d = (int64_t)p4[i] - p2[i] - borrow;
    borrow = d < 0;
    acc[i] = (uint32_t)(d + (borrow ? ((int64_t)1 << 32) : 0));
  }
  uint64_t carry = 1;
  for (int i = 0; i < W && carry; i++) {
    uint64_t cur = (uint64_t)acc[i] + carry;
    acc[i] = (uint32_t)cur;
    carry = cur >> 32;
  }
  // divide acc by r (schoolbook long division, 32-bit digits)
  uint32_t r32[W] = {0};
  for (int i = 0; i < 4; i++) {
    r32[2 * i] = (uint32_t)Rord[i];
    r32[2 * i + 1] = (uint32_t)(Rord[i] >> 32);
  }
  int rtop = W - 1;
  while (rtop > 0 && !r32[rtop]) rtop--;
  int atop = W - 1;
  while (atop > 0 && !acc[atop]) atop--;
  uint32_t quo[W] = {0};
  // simple bitwise long division (acc ~1524 bits: fine at init time)
  uint32_t rem[W] = {0};
  for (int bit = (atop + 1) * 32 - 1; bit >= 0; bit--) {
    // rem = rem*2 + bit
    uint32_t c = (acc[bit / 32] >> (bit % 32)) & 1;
    for (int i = W - 1; i > 0; i--)
      rem[i] = (rem[i] << 1) | (rem[i - 1] >> 31);
    rem[0] = (rem[0] << 1) | c;
    // if rem >= r: rem -= r; quo bit 1
    int ge = 0;
    for (int i = W - 1; i >= 0; i--) {
      if (rem[i] != r32[i]) {
        ge = rem[i] > r32[i];
        goto cmp_done;
      }
    }
    ge = 1;
  cmp_done:
    if (ge) {
      int64_t br = 0;
      for (int i = 0; i < W; i++) {
        int64_t d = (int64_t)rem[i] - r32[i] - br;
        br = d < 0;
        rem[i] = (uint32_t)(d + (br ? ((int64_t)1 << 32) : 0));
      }
      quo[bit / 32] |= 1u << (bit % 32);
    }
  }
  memset(HARD, 0, sizeof HARD);
  for (int i = 0; i < 40 * 2 && i < W; i++) {
    HARD[i / 2] |= (u64)quo[i] << (32 * (i % 2));
  }
  HARD_BITS = 0;
  for (int i = 40 * 64 - 1; i >= 0; i--) {
    if ((HARD[i / 64] >> (i % 64)) & 1) {
      HARD_BITS = i + 1;
      break;
    }
  }
}

static void init_consts() {
  // n0inv = -p^-1 mod 2^64 (Newton)
  u64 inv = 1;
  for (int i = 0; i < 6; i++) inv *= 2 - Pmod[0] * inv;
  N0INV = (u64)(0 - inv);
  // ONE_M = 2^384 mod p: start from 1, double 384 times with reduction
  u64 x[NL] = {1, 0, 0, 0, 0, 0};
  for (int k = 0; k < 384; k++) {
    int carry = raw_add(x, x, x);
    if (carry || raw_cmp(x, Pmod) >= 0) raw_sub(x, x, Pmod);
  }
  memcpy(ONE_M, x, sizeof x);
  // R2 = 2^768 mod p: double 384 more times
  for (int k = 0; k < 384; k++) {
    int carry = raw_add(x, x, x);
    if (carry || raw_cmp(x, Pmod) >= 0) raw_sub(x, x, Pmod);
  }
  memcpy(R2, x, sizeof x);
  compute_hard();
  // XI_INV = (1+u)^-1 in Montgomery form
  Fp2 xi;
  xi.c0 = ONE_M_fp();
  xi.c1 = ONE_M_fp();
  f2_inv(XI_INV, xi);
  // FROB_C[i] = xi^(i (p-1)/6): compute via Fp2 exponentiation by the
  // integer (p-1)/6 applied i times multiplicatively.
  // (p-1)/6 fits in 6 limbs.
  u64 e[NL];
  u64 one1[NL] = {1, 0, 0, 0, 0, 0};
  raw_sub(e, Pmod, one1);
  // divide by 6 (single-word long division over limbs, MSB first)
  u64 q[NL] = {0};
  u128 rem = 0;
  for (int i = NL - 1; i >= 0; i--) {
    u128 cur = (rem << 64) | e[i];
    q[i] = (u64)(cur / 6);
    rem = cur % 6;
  }
  // base = xi^((p-1)/6) via square-and-multiply
  Fp2 base;
  base.c0 = ONE_M_fp();
  fp_zero(base.c1);
  {
    Fp2 r = base;  // one
    int started = 0;
    for (int i = NL * 64 - 1; i >= 0; i--) {
      if (started) f2_sqr(r, r);
      if ((q[i / 64] >> (i % 64)) & 1) {
        if (started)
          f2_mul(r, r, xi);
        else {
          r = xi;
          started = 1;
        }
      }
    }
    base = r;
  }
  FROB_C[0].c0 = ONE_M_fp();
  fp_zero(FROB_C[0].c1);
  for (int i = 1; i < 6; i++) f2_mul(FROB_C[i], FROB_C[i - 1], base);
}

// ------------------------------------------------------------ public API

static bool INITED = false;

static void ensure_init() {
  if (!INITED) {
    init_consts();
    INITED = true;
  }
}

static void fp_from_raw(Fp& o, const u64* limbs) {
  Fp t;
  memcpy(t.v, limbs, sizeof t.v);
  Fp r2;
  memcpy(r2.v, R2, sizeof r2.v);
  fp_mul(o, t, r2);  // to Montgomery
}

extern "C" {

// g1s: n * 12 limbs (x, y), g2s: n * 24 limbs (x0, x1, y0, y1);
// all coordinates affine, little-endian 6x64 limbs, NOT Montgomery.
// Returns 1 iff prod e(P_i, Q_i) == 1; -1 on bad input sizes.
int bls381_pairing_product_is_one(const u64* g1s, const u64* g2s, int n) {
  ensure_init();
  Fp12 f, m;
  f12_one(f);
  for (int k = 0; k < n; k++) {
    G1Aff p;
    G2Aff q;
    fp_from_raw(p.x, g1s + k * 12);
    fp_from_raw(p.y, g1s + k * 12 + 6);
    fp_from_raw(q.x.c0, g2s + k * 24);
    fp_from_raw(q.x.c1, g2s + k * 24 + 6);
    fp_from_raw(q.y.c0, g2s + k * 24 + 12);
    fp_from_raw(q.y.c1, g2s + k * 24 + 18);
    miller(m, q, p);
    f12_mul(f, f, m);
  }
  Fp12 out;
  final_exp(out, f);
  return f12_is_one(out) ? 1 : 0;
}

// Single full pairing, raw output for differential testing against the
// Python implementation: out = 72 limbs (6 Fp2 coeffs x 2 Fp x 6 limbs),
// little-endian, non-Montgomery, in the module's w-power order.
void bls381_pairing(const u64* g1, const u64* g2, u64* out) {
  ensure_init();
  G1Aff p;
  G2Aff q;
  fp_from_raw(p.x, g1);
  fp_from_raw(p.y, g1 + 6);
  fp_from_raw(q.x.c0, g2);
  fp_from_raw(q.x.c1, g2 + 6);
  fp_from_raw(q.y.c0, g2 + 12);
  fp_from_raw(q.y.c1, g2 + 18);
  Fp12 m, e;
  miller(m, q, p);
  final_exp(e, m);
  // from Montgomery: multiply by 1
  Fp onep;
  u64 raw1[NL] = {1, 0, 0, 0, 0, 0};
  memcpy(onep.v, raw1, sizeof raw1);
  for (int i = 0; i < 6; i++) {
    Fp a, b;
    fp_mul(a, e.c[i].c0, onep);
    fp_mul(b, e.c[i].c1, onep);
    memcpy(out + i * 12, a.v, sizeof a.v);
    memcpy(out + i * 12 + 6, b.v, sizeof b.v);
  }
}

}  // extern "C"
